"""Continuous-training service: the train→evaluate→publish loop
(lightgbm_tpu/continuous/ — docs/CONTINUOUS_TRAINING.md).

Pins, per the round-15 acceptance criteria:

- end-to-end cycle: new data slice → streaming append-construct
  against FROZEN base mappers → continue-from-last-good training →
  eval gate → hot publish, with served predictions byte-identical to
  a direct ``Booster.predict`` of the published model file;
- crash safety: a cycle interrupted at EVERY phase boundary (and
  mid-train, through the checkpoint machinery) resumes from its
  ledger to a byte-identical published model;
- a forced metric regression triggers auto-rollback with zero failed
  responses under concurrent load, restoring the prior version's
  outputs byte-identically;
- drift detection, the quarantine ledger, the ``/continuous`` control
  surface, the registry's per-version audit metadata, and the
  engine's loud resume=/init_model= conflict.
"""
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.continuous import (ContinuousLane, append_construct,
                                     discover_slices, drift_check,
                                     holdout_split)
from lightgbm_tpu.serving import ModelRegistry
from lightgbm_tpu.telemetry import TELEMETRY

PARAMS = {"objective": "regression", "verbose": -1, "num_leaves": 7,
          "min_data_in_leaf": 5, "max_bin": 31}


def _data(seed, n=300, shift=0.0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 5)
    y = X[:, 0] - 0.3 * X[:, 1] + shift
    return X, y


def _write_slice(ingest, name, seed=7, n=120, shift=0.0, X=None,
                 y=None):
    if X is None:
        X, y = _data(seed, n, shift)
    np.savetxt(os.path.join(ingest, name),
               np.column_stack([y, X]), delimiter=",")
    return X, y


@pytest.fixture(scope="module")
def base_model():
    X, y = _data(0)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 4,
                    verbose_eval=False)
    return bst, X, y


def _lane(tmp_path, base_model, registry=None, **cfg_over):
    bst, Xb, yb = base_model
    ingest = os.path.join(str(tmp_path), "ingest")
    os.makedirs(ingest, exist_ok=True)
    over = dict(PARAMS, continuous_ingest_dir=ingest,
                continuous_iterations=3, continuous_eval_holdout=0.25)
    over.update(cfg_over)
    cfg = Config.from_params(over)
    lane = ContinuousLane(cfg, registry, name="m", base_model=bst,
                          base_data=Xb, base_label=yb,
                          train_params=dict(PARAMS))
    lane._base_model_path()
    return lane, ingest


# ---------------------------------------------------------------------------
# end-to-end cycle + serving parity
# ---------------------------------------------------------------------------
def test_cycle_end_to_end_publish_and_parity(tmp_path, base_model):
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry)
    registry.publish("m", lane._p("model_base.txt"), source="manual")
    _write_slice(ingest, "s1.csv", seed=7)

    rec = lane.run_cycle()
    assert rec is not None and rec["accept"] is True
    assert rec["metric"] == "l2"
    assert rec["eval_rows"] == 30          # 25% tail of 120 rows
    # continue mode added continuous_iterations new trees
    published = lane._p(lane._ledger["last_good"])
    cand = lgb.Booster(model_file=published)
    assert cand.num_trees() == base_model[0].num_trees() + 3

    # served predictions byte-identical to direct predict of the
    # published model file (the acceptance pin)
    Xq, _ = _data(99, n=16)
    entry, served = registry.predict("m", Xq)
    assert entry.version == 2
    assert np.array_equal(np.asarray(served), cand.predict(Xq))

    c = TELEMETRY.counters()
    assert c.get("continuous_cycles") == 1
    assert c.get("continuous_publishes") == 1
    assert c.get("continuous_rows_ingested") == 120
    # nothing new: no cycle runs
    assert lane.run_cycle() is None
    registry.close()


def test_append_construct_bins_match_reference_alignment(base_model):
    """Appended slices bin byte-identically to a from-scratch
    reference-aligned construction of the same rows — the frozen
    mappers really are frozen."""
    bst, Xb, yb = base_model
    cfg = Config.from_params(PARAMS)
    base = lgb.Dataset(Xb, label=yb, free_raw_data=False,
                       params=PARAMS).construct(cfg)
    Xs, ys = _data(5, n=77)
    core = append_construct(base, [Xs], [ys], base_raw=Xb)
    assert core.num_data == base.num_data + 77
    # base rows copied, never re-binned
    assert np.array_equal(np.asarray(core.group_bins[:base.num_data]),
                          np.asarray(base.group_bins))
    from lightgbm_tpu.dataset import Dataset as CoreDataset
    ref = CoreDataset.from_matrix(Xs, label=ys, config=cfg,
                                  reference=base)
    assert np.array_equal(np.asarray(core.group_bins[base.num_data:]),
                          np.asarray(ref.group_bins))
    # metadata casts labels to float32 (the training dtype)
    assert np.array_equal(
        core.metadata.label,
        np.concatenate([yb, ys]).astype(np.float32))


def test_forced_cycle_without_new_slices(tmp_path, base_model):
    lane, ingest = _lane(tmp_path, base_model)
    assert lane.run_cycle() is None              # nothing to do
    rec = lane.run_cycle(force=True)             # continue-mode trains
    assert rec is not None and rec["accept"] is True
    assert rec["metric"] is None                 # no holdout rows


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------
def test_drift_detection_counts_and_warns(tmp_path, base_model):
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    bst, Xb, yb = base_model
    cfg = Config.from_params(PARAMS)
    base = lgb.Dataset(Xb, label=yb, free_raw_data=False,
                       params=PARAMS).construct(cfg)
    X = np.zeros((10, 5))
    X[0, 0] = 1e9            # past max_val
    X[1, 0] = -1e9           # past min_val
    X[2, 1] = np.nan         # missing, NOT drift
    per = drift_check(base, X, "slice")
    assert per.get(0) == 2
    assert 1 not in per
    c = TELEMETRY.counters()
    assert c.get("continuous_drift_values") == 2
    assert c.get("continuous_drift_slices") == 1
    # silent recompute (crash-resume reload) must not double-count
    drift_check(base, X, "slice", count=False)
    assert TELEMETRY.counters().get("continuous_drift_values") == 2


def test_drift_unseen_category():
    rng = np.random.RandomState(3)
    X = np.column_stack([rng.randint(0, 4, 200).astype(float),
                         rng.randn(200)])
    y = rng.randn(200)
    cfg = Config.from_params(PARAMS)
    core = lgb.Dataset(X, label=y, categorical_feature=[0],
                       params=PARAMS).construct(cfg)
    Xnew = X[:8].copy()
    Xnew[0, 0] = 77.0        # category never seen at fit time
    per = drift_check(core, Xnew, count=False)
    assert per.get(0) == 1


# ---------------------------------------------------------------------------
# eval gate: quarantine + ledger
# ---------------------------------------------------------------------------
def test_gate_rejects_and_quarantines(tmp_path, base_model):
    """A slice whose TRAIN rows carry inverted labels but whose
    held-out tail is clean trains a candidate that regresses on eval
    — the gate must quarantine it and keep serving the last good
    model."""
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry)
    registry.publish("m", lane._p("model_base.txt"), source="manual")
    X, y = _data(13, n=120)
    y_bad = y.copy()
    y_bad[:90] = -5.0 * y[:90]       # poisoned train portion
    _write_slice(ingest, "bad.csv", X=X, y=y_bad)

    rec = lane.run_cycle()
    assert rec["accept"] is False
    assert registry.get("m").version == 1       # no publish happened
    led = lane._ledger
    assert led["last_good"] == "model_base.txt"
    assert len(led["quarantined"]) == 1
    q = led["quarantined"][0]
    assert q["reason"] == "eval gate"
    assert q["candidate_metric"] > q["current_metric"]
    c = TELEMETRY.counters()
    assert c.get("continuous_publish_rejects") == 1
    assert c.get("continuous_quarantined") == 1
    # the cycle still retired: its slices are consumed
    assert lane.run_cycle() is None
    registry.close()


def test_publish_max_regression_tolerance(tmp_path, base_model):
    """The same poisoned cycle publishes when the operator allows the
    regression explicitly."""
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry,
                         continuous_publish_max_regression=1e9)
    registry.publish("m", lane._p("model_base.txt"), source="manual")
    X, y = _data(13, n=120)
    y_bad = y.copy()
    y_bad[:90] = -5.0 * y[:90]
    _write_slice(ingest, "bad.csv", X=X, y=y_bad)
    rec = lane.run_cycle()
    assert rec["accept"] is True
    assert registry.get("m").version == 2
    registry.close()


# ---------------------------------------------------------------------------
# live-metric rollback
# ---------------------------------------------------------------------------
def test_live_regression_auto_rollback_restores_outputs(
        tmp_path, base_model):
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry)
    registry.publish("m", lane._p("model_base.txt"), source="manual")
    Xq, _ = _data(99, n=16)
    _entry, before = registry.predict("m", Xq)

    _write_slice(ingest, "s1.csv", seed=7)
    rec = lane.run_cycle()
    assert rec["accept"] and registry.get("m").version == 2

    # healthy live metric: no rollback
    assert lane.report_live_metric(rec["candidate_metric"]) is False
    # regressing live metric: rollback + quarantine
    assert lane.report_live_metric(
        rec["candidate_metric"] + 10.0) is True
    assert registry.get("m").version == 1
    assert lane._ledger["last_good"] == "model_base.txt"
    assert lane._ledger["quarantined"][-1]["reason"] == \
        "live metric regression"
    # rollback restores the prior version's outputs byte-identically
    _entry, after = registry.predict("m", Xq)
    assert np.array_equal(np.asarray(after), np.asarray(before))
    assert TELEMETRY.counters().get("continuous_rollbacks") == 1
    registry.close()


def test_rollback_under_concurrent_load_no_failed_or_mixed(
        tmp_path, base_model):
    """Satellite pin: clients hammer the registry while the lane
    publishes and then auto-rolls back — every response must be
    whole (no failures) and from exactly one version's model, and
    the post-rollback outputs must byte-match the pre-publish ones."""
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry)
    registry.publish("m", lane._p("model_base.txt"), source="manual")
    Xq, _ = _data(99, n=4)
    base_out = lgb.Booster(
        model_file=lane._p("model_base.txt")).predict(Xq)
    _write_slice(ingest, "s1.csv", seed=7)

    stop = threading.Event()
    failures, outputs = [], []

    def client():
        while not stop.is_set():
            try:
                _e, out = registry.predict("m", Xq)
                outputs.append(np.asarray(out))
            except Exception as e:  # pragma: no cover - failure pin
                failures.append(repr(e))

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        rec = lane.run_cycle()          # hot publish under load
        assert rec["accept"]
        assert lane.report_live_metric(
            rec["candidate_metric"] + 10.0) is True   # rollback
    finally:
        stop.set()
        for t in threads:
            t.join(60)
    assert not failures, failures[:3]
    assert outputs
    cand_out = lgb.Booster(
        model_file=lane._p(f"model_cycle_{rec['cycle']}.txt")
    ).predict(Xq)
    for out in outputs:
        # every response equals exactly ONE version's outputs
        assert np.array_equal(out, base_out) \
            or np.array_equal(out, cand_out)
    # rollback restored the prior version byte-identically
    _e, after = registry.predict("m", Xq)
    assert np.array_equal(np.asarray(after), base_out)
    registry.close()


# ---------------------------------------------------------------------------
# crash safety: ledger replay at every phase boundary
# ---------------------------------------------------------------------------
def test_cycle_replay_byte_identical_at_every_phase(
        tmp_path, base_model):
    """Simulated crash at each phase commit: abandon the lane object
    mid-cycle (its ledger is on disk) and run a FRESH lane over the
    same state dir — the resumed publish must byte-match an
    uninterrupted control run.  (The real-SIGKILL version of this pin
    runs in scripts/continuous_probe.py through the continuous.cycle
    fault seam.)"""
    from lightgbm_tpu.reliability.faults import FAULTS
    # control: uninterrupted
    ctrl_lane, ctrl_ingest = _lane(
        tmp_path / "ctrl", base_model, continuous_checkpoint_freq=2)
    _write_slice(ctrl_ingest, "s1.csv", seed=7)
    ctrl_lane.run_cycle()
    ctrl = open(ctrl_lane._p(ctrl_lane._ledger["last_good"])).read()

    for phase in ("ingest", "train", "eval", "publish"):
        d = tmp_path / f"crash_{phase}"
        lane, ingest = _lane(d, base_model,
                             continuous_checkpoint_freq=2)
        _write_slice(ingest, "s1.csv", seed=7)
        # run the cycle but ABORT at the target phase entry via the
        # fault seam (an exception, not a kill — same commit point)
        FAULTS.configure(
            f"continuous.cycle:{1 + ['ingest', 'train', 'eval', 'publish'].index(phase)}"
            ":RuntimeError")
        try:
            with pytest.raises(RuntimeError):
                lane.run_cycle()
        finally:
            FAULTS.reset()
        # "restart": fresh lane over the same state dir
        lane2, _ = _lane(d, base_model, continuous_checkpoint_freq=2)
        rec = lane2.run_cycle()
        assert rec is not None
        assert rec["resumed"] is (phase != "ingest")
        got = open(lane2._p(lane2._ledger["last_good"])).read()
        assert got == ctrl, f"crash at {phase}: replay diverged"


def test_mid_train_checkpoint_resume_byte_identical(
        tmp_path, base_model):
    """A crash INSIDE the train phase (after checkpoints were cut)
    resumes through the r12 machinery instead of replaying the whole
    cycle — and still publishes byte-identically."""
    from lightgbm_tpu.reliability.faults import FAULTS
    ctrl_lane, ctrl_ingest = _lane(
        tmp_path / "ctrl", base_model, continuous_iterations=6,
        continuous_checkpoint_freq=2)
    _write_slice(ctrl_ingest, "s1.csv", seed=7)
    ctrl_lane.run_cycle()
    ctrl = open(ctrl_lane._p(ctrl_lane._ledger["last_good"])).read()

    d = tmp_path / "crash"
    lane, ingest = _lane(d, base_model, continuous_iterations=6,
                         continuous_checkpoint_freq=2)
    _write_slice(ingest, "s1.csv", seed=7)
    # dispatch_chunk cuts at checkpoint boundaries (freq=2): fail the
    # SECOND fused-chunk enqueue — iterations 1-2 checkpointed,
    # 3-6 lost
    FAULTS.configure("gbdt.train_chunk:2:RuntimeError")
    try:
        with pytest.raises(RuntimeError):
            lane.run_cycle()
    finally:
        FAULTS.reset()
    ck = [f for f in os.listdir(lane.state_dir)
          if f.startswith("ckpt_cycle_1_iter_")]
    assert ck, "train phase cut no mid-cycle checkpoints"
    lane2, _ = _lane(d, base_model, continuous_iterations=6,
                     continuous_checkpoint_freq=2)
    rec = lane2.run_cycle()
    assert rec["resumed"] is True
    got = open(lane2._p(lane2._ledger["last_good"])).read()
    assert got == ctrl


def test_weighted_base_refused_in_continue_mode(tmp_path, base_model):
    """Append-construct does not propagate row weights: a weighted
    base must refuse loudly in continue mode instead of silently
    training every cycle unweighted."""
    bst, Xb, yb = base_model
    ingest = os.path.join(str(tmp_path), "ingest")
    os.makedirs(ingest)
    # file-backed base with a weight column (the CLI path)
    w = np.full(len(yb), 2.0)
    base_csv = str(tmp_path / "base.csv")
    np.savetxt(base_csv, np.column_stack([yb, w, Xb]), delimiter=",")
    params = dict(PARAMS, weight_column="1")
    cfg = Config.from_params(dict(params,
                                  continuous_ingest_dir=ingest,
                                  data=base_csv))
    lane = ContinuousLane(cfg, None, name="m", base_model=bst,
                          train_params=params)
    lane._base_model_path()
    _write_slice(ingest, "s1.csv", seed=7)
    with pytest.raises(ValueError, match="unweighted"):
        lane.run_cycle()


# ---------------------------------------------------------------------------
# refit mode
# ---------------------------------------------------------------------------
def test_refit_mode_cycle_updates_leaves_only(tmp_path, base_model):
    TELEMETRY.configure("spans")
    TELEMETRY.reset()
    bst, _Xb, _yb = base_model
    lane, ingest = _lane(tmp_path, base_model,
                         continuous_mode="refit",
                         continuous_publish_max_regression=1e9)
    _write_slice(ingest, "s1.csv", seed=7)
    rec = lane.run_cycle()
    assert rec is not None
    cand = lgb.Booster(
        model_file=lane._p(f"model_cycle_{rec['cycle']}.txt"))
    # refit keeps structure: same tree count, same split features
    assert cand.num_trees() == bst.num_trees()
    c = TELEMETRY.counters()
    assert c.get("refit_leaves_updated", 0) > 0
    names = [ev[0] for ev in TELEMETRY.events_snapshot()]
    assert "refit" in names
    assert "continuous_train" in names
    TELEMETRY.configure("off")


def test_drift_triggered_refit_cycle(tmp_path, base_model):
    """Drift-triggered base refit (round-16 satellite,
    continuous_drift_refit_threshold): once the cumulative drifted-
    slice tally crosses the threshold, the NEXT cycle runs a refit
    (leaf values refreshed through real-valued thresholds, no new
    trees) instead of only warning, commits the mode to the ledger
    (crash-replay deterministic) and resets the tally."""
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    bst, _Xb, _yb = base_model
    lane, ingest = _lane(tmp_path, base_model,
                         continuous_mode="continue",
                         continuous_drift_refit_threshold=2,
                         continuous_publish_max_regression=1e9)
    # cycle 1: one drifted slice (values far outside the base range)
    # — below the threshold, so it continue-trains as configured
    _write_slice(ingest, "s1.csv", seed=7, shift=500.0)
    rec1 = lane.run_cycle()
    assert rec1 is not None
    assert lane._ledger.get("cycle_mode") == "continue"
    assert lane._ledger.get("drift_slices") == 1
    m1 = lgb.Booster(model_file=lane._p(lane._ledger["last_good"]))
    assert m1.num_trees() == bst.num_trees() + 3   # continue added trees

    # cycle 2: a second drifted slice crosses the threshold — the
    # cycle flips to refit (tree count unchanged) and the tally resets
    _write_slice(ingest, "s2.csv", seed=8, shift=500.0)
    rec2 = lane.run_cycle()
    assert rec2 is not None
    assert lane._ledger.get("cycle_mode") == "refit"
    assert lane._ledger.get("drift_slices") == 0
    cand = lgb.Booster(
        model_file=lane._p(f"model_cycle_{rec2['cycle']}.txt"))
    assert cand.num_trees() == m1.num_trees(), \
        "drift-triggered cycle must refit, not grow trees"
    assert TELEMETRY.counters().get("continuous_drift_refits") == 1

    # cycle 3: an undrifted slice goes back to continue mode
    _write_slice(ingest, "s3.csv", seed=9, shift=0.0)
    rec3 = lane.run_cycle()
    assert rec3 is not None
    assert lane._ledger.get("cycle_mode") == "continue"
    TELEMETRY.configure("off")


def test_drift_refit_off_by_default(tmp_path, base_model):
    """Threshold 0 (the default) keeps the r15 warn-and-count-only
    behavior: a drifted slice still continue-trains."""
    bst, _Xb, _yb = base_model
    lane, ingest = _lane(tmp_path, base_model,
                         continuous_publish_max_regression=1e9)
    _write_slice(ingest, "s1.csv", seed=7, shift=500.0)
    rec = lane.run_cycle()
    assert rec is not None
    assert lane._ledger.get("cycle_mode") == "continue"
    m = lgb.Booster(model_file=lane._p(lane._ledger["last_good"]))
    assert m.num_trees() == bst.num_trees() + 3


# ---------------------------------------------------------------------------
# control surface on the shared listener
# ---------------------------------------------------------------------------
def test_http_control_surface(tmp_path, base_model):
    from lightgbm_tpu.serving import ServingFrontend
    registry = ModelRegistry(Config.from_params(PARAMS))
    lane, ingest = _lane(tmp_path, base_model, registry,
                         continuous_poll_s=30.0)
    frontend = ServingFrontend(registry, lane.config)
    port = frontend.start(0).server_address[1]
    lane.start()        # publishes base, mounts /continuous
    try:
        url = f"http://127.0.0.1:{port}/continuous"
        st = json.loads(urllib.request.urlopen(url, timeout=30).read())
        assert st["name"] == "m" and st["mode"] == "continue"
        assert st["state"] == "running"

        def post(payload):
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(), method="POST")
            return json.loads(
                urllib.request.urlopen(req, timeout=30).read())

        assert post({"action": "pause"})["state"] == "paused"
        assert post({"action": "resume"})["state"] == "running"
        r = post({"action": "live_metric", "value": 0.5})
        assert r["rolled_back"] is False     # nothing gated published
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"action": "bogus"})
        assert ei.value.code == 400
        # /models carries the per-version audit metadata
        models = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models", timeout=30).read())
        vs = models["m"]["versions"]
        assert vs[0]["source"] == "manual"
        assert vs[0]["serving"] is True
        assert "published_unix" in vs[0]
    finally:
        lane.stop()
        frontend.stop()
    # the /continuous route is unmounted after stop
    assert TELEMETRY._resolve_route("/continuous") is None


# ---------------------------------------------------------------------------
# registry audit metadata (satellite)
# ---------------------------------------------------------------------------
def test_registry_per_version_metadata(base_model):
    bst, _X, _y = base_model
    registry = ModelRegistry(Config.from_params(PARAMS))
    registry.publish("m", bst, published_unix=123.456,
                     eval_metric=0.25, source="continuous")
    d = registry.describe()["m"]
    assert d["versions"] == [{"version": 1, "serving": True,
                              "source": "continuous",
                              "published_unix": 123.456,
                              "eval_metric": 0.25}]
    with pytest.raises(ValueError, match="source"):
        registry.publish("m", bst, source="robot")
    registry.close()


# ---------------------------------------------------------------------------
# ingest mechanics
# ---------------------------------------------------------------------------
def test_discover_slices_ordering_and_manifest(tmp_path):
    d = str(tmp_path)
    for name in ("b.csv", "a.csv", ".hidden", "x.tmp", "y.bin"):
        with open(os.path.join(d, name), "w") as f:
            f.write("1,2\n")
    assert discover_slices(d) == ["a.csv", "b.csv"]
    assert discover_slices(d, processed=["a.csv"]) == ["b.csv"]
    with open(os.path.join(d, "MANIFEST"), "w") as f:
        f.write("# order pinned\nb.csv\nmissing.csv\na.csv\n")
    assert discover_slices(d) == ["b.csv", "a.csv"]
    assert discover_slices("/nonexistent/dir") == []


def test_holdout_split_deterministic_tail():
    X = np.arange(20, dtype=float).reshape(10, 2)
    y = np.arange(10, dtype=float)
    Xt, yt, Xe, ye = holdout_split(X, y, 0.25)
    assert len(Xt) == 7 and len(Xe) == 3          # ceil(10 * .25)
    assert np.array_equal(ye, y[7:])              # the TAIL
    # 1-row slice keeps its row in training
    Xt, yt, Xe, ye = holdout_split(X[:1], y[:1], 0.5)
    assert len(Xt) == 1 and len(Xe) == 0
    Xt, _, Xe, _ = holdout_split(X, y, 0.0)
    assert len(Xt) == 10 and len(Xe) == 0


# ---------------------------------------------------------------------------
# engine satellite: resume= + init_model= conflict
# ---------------------------------------------------------------------------
def test_engine_resume_path_plus_init_model_is_loud(base_model):
    bst, X, y = base_model
    with pytest.raises(ValueError, match="init_model"):
        lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), 3,
                  init_model=bst, resume="/tmp/some.ckpt",
                  verbose_eval=False)
    # resume='auto' + init_model still composes (the fingerprint
    # carries the init-model identity)
    out = lgb.train(dict(PARAMS), lgb.Dataset(
        X, label=y, free_raw_data=False), 2, init_model=bst,
        resume="auto", verbose_eval=False)
    assert out.num_trees() == bst.num_trees() + 2


# ---------------------------------------------------------------------------
# CLI task=refit telemetry satellite
# ---------------------------------------------------------------------------
def test_cli_refit_exports_telemetry(tmp_path, base_model):
    """task=refit honors telemetry_out/telemetry_prom_out like
    train/predict/serve, and the refit run itself is instrumented
    (refit span + refit_leaves_updated counter)."""
    from lightgbm_tpu import cli
    bst, X, y = base_model
    model = str(tmp_path / "m.txt")
    bst.save_model(model)
    data = str(tmp_path / "refit.csv")
    np.savetxt(data, np.column_stack([y, X]), delimiter=",")
    out = str(tmp_path / "m2.txt")
    tel = str(tmp_path / "tel")
    prom = str(tmp_path / "m.prom")
    TELEMETRY.configure("spans")
    TELEMETRY.reset()
    try:
        rc = cli.run([
            "task=refit", f"input_model={model}", f"data={data}",
            f"output_model={out}", "telemetry=spans",
            f"telemetry_out={tel}", f"telemetry_prom_out={prom}",
            "verbose=-1"])
    finally:
        # un-arm the process-global export targets this test set (the
        # CLI armed them via Config): later tests pin that argless
        # export/write_prom RAISE when nothing is configured
        TELEMETRY.configure("off")
        TELEMETRY.out = ""
        TELEMETRY.prom_out = ""
    assert rc == 0 and os.path.exists(out)
    assert os.path.getsize(tel + ".jsonl") > 0
    assert os.path.getsize(tel + ".perfetto.json") > 0
    with open(prom) as f:
        text = f.read()
    assert "ltpu_refit_leaves_updated_total" in text
    with open(tel + ".jsonl") as f:
        names = [json.loads(ln).get("name") for ln in f]
    assert "refit" in names


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------
def test_continuous_config_validation():
    with pytest.raises(ValueError, match="continuous_mode"):
        Config.from_params({"continuous_mode": "bogus"})
    with pytest.raises(ValueError, match="continuous_eval_holdout"):
        Config.from_params({"continuous_eval_holdout": 1.5})
    with pytest.raises(ValueError, match="continuous_poll_s"):
        Config.from_params({"continuous_poll_s": 0})
    with pytest.raises(ValueError, match="continuous_iterations"):
        Config.from_params({"continuous_iterations": 0})
    with pytest.raises(ValueError,
                       match="continuous_publish_max_regression"):
        Config.from_params({"continuous_publish_max_regression": -1})
    with pytest.raises(ValueError, match="lambdarank"):
        ContinuousLane(
            Config.from_params({"objective": "lambdarank",
                                "continuous_ingest_dir": "/tmp"}),
            None, train_params={"objective": "lambdarank"})


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"]))
