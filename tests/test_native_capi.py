"""Native embedding C API: compile a pure-C host against
liblgbm_tpu.so and run the reference-style C-API workout
(tests/native_capi_driver.c) in a subprocess with no Python on its
stack — the seam R/Java hosts use (reference: R-package/src/
lightgbm_R.cpp links lib_lightgbm the same way)."""
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "lightgbm_tpu", "native")
LIB = os.path.join(NATIVE, "liblgbm_tpu.so")
DRIVER_SRC = os.path.join(REPO, "tests", "native_capi_driver.c")



@pytest.mark.slow
def test_c_host_end_to_end(native_lib, tmp_path):
    exe = str(tmp_path / "capi_driver")
    inc_dir = os.path.join(NATIVE, "include")
    build = subprocess.run(
        ["gcc", "-O1", DRIVER_SRC, "-I", inc_dir, "-o", exe,
         "-L", NATIVE, "-llgbm_tpu", "-lm",
         f"-Wl,-rpath,{NATIVE}"],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the embedded interpreter runs JAX on CPU — never the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    run = subprocess.run([exe, REPO], capture_output=True, text=True,
                         env=env, timeout=600)
    assert run.returncode == 0, f"stdout={run.stdout}\nstderr={run.stderr}"
    assert "NATIVE_CAPI_OK" in run.stdout
