"""Test harness: force a CPU-only 8-device virtual mesh.

Distributed learners are exercised on host-simulated devices (the
reference has no multi-node CI at all — SURVEY §4; this is the
deterministic multi-host substitute).  The TPU plugin environment may
override JAX_PLATFORMS via a config update at interpreter start, so we
set the config explicitly after import — tests must never touch (or
hang on) the real accelerator tunnel.
"""
import os

# LGBM_TPU_ONCHIP=1 runs the suite against the real chip (for
# tests/test_tpu_onchip.py's Mosaic-numerics parity checks)
_ONCHIP = os.environ.get("LGBM_TPU_ONCHIP") == "1"

if not _ONCHIP:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ONCHIP:
    jax.config.update("jax_platforms", "cpu")
# persistent compile cache: every TreeGrower instance re-jits its tree
# function, so without this the suite recompiles identical shapes
# dozens of times (round-1 suite exceeded 25 min; compiles dominated)
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..",
                               ".jax_cache_cpu"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import subprocess  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE = os.path.join(_REPO, "lightgbm_tpu", "native")
_CAPI_SRC = os.path.join(_NATIVE, "src", "capi", "c_api_embed.cpp")
_CAPI_LIB = os.path.join(_NATIVE, "liblgbm_tpu.so")


def _python_config(*flags):
    exe = f"python{sys.version_info.major}.{sys.version_info.minor}-config"
    for cand in (exe, "python3-config"):
        try:
            out = subprocess.run([cand, *flags], capture_output=True,
                                 text=True, check=True)
            return out.stdout.split()
        except (OSError, subprocess.CalledProcessError):
            continue
    return None


@pytest.fixture(scope="session")
def native_lib():
    """Session-shared liblgbm_tpu.so: built once per suite (three
    binding test files used to rebuild it independently, ~40 s of g++
    each) and skipped entirely when the source hasn't changed."""
    inc = _python_config("--includes")
    ld = _python_config("--ldflags", "--embed")
    if inc is None or ld is None:
        pytest.skip("python-config not available")
    src_mtime = os.path.getmtime(_CAPI_SRC)
    inc_dir = os.path.join(_NATIVE, "include")
    for f in os.listdir(inc_dir):
        src_mtime = max(src_mtime,
                        os.path.getmtime(os.path.join(inc_dir, f)))
    if (os.path.exists(_CAPI_LIB)
            and os.path.getmtime(_CAPI_LIB) > src_mtime):
        return _CAPI_LIB
    build = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", *inc,
         _CAPI_SRC, "-o", _CAPI_LIB, *ld],
        capture_output=True, text=True)
    assert build.returncode == 0, \
        f"native capi build failed: {build.stderr[-2000:]}"
    return _CAPI_LIB


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(scope="session")
def analysis_programs():
    """One ProgramSet per suite: the static-analysis probe builds
    (carry-probe GBDT, predict-probe booster, lowered entry points)
    are shared by tests/test_analysis.py and tests/test_carry_hlo.py
    instead of each file re-training its own."""
    from lightgbm_tpu.analysis.programs import ProgramSet
    return ProgramSet()


# ---------------------------------------------------------------------------
# `fast` smoke tier: one representative test per subsystem (marker
# applied here so the test files stay uncluttered).  pytest -m fast -q
# is the inner development loop; "not slow" is the thorough tier.
_FAST_TESTS = {
    "test_binary",                      # engine end-to-end
    "test_regression",
    "test_missing_value_nan",           # missing-value semantics
    "test_categorical_handling",        # categorical splits
    "test_save_load_pickle_roundtrip",  # model text IO
    "test_simple_numerical",            # binning
    "test_zero_gets_own_bin",
    "test_bundles_exclusive_features",  # EFB
    "test_apply_splits_matches_reference_over_256_groups",  # partition
    "test_pallas_kernel_matches_einsum_interpret",          # hist
    "test_subbyte_streamed_kernels_match_pack1_interpret",
    "test_fused_grower_wiring_interpret_matches_xla_path",
    "test_data_parallel_matches_serial",                    # mesh
    "test_dataset_booster_lifecycle",   # C API
    "test_round4_symbol_tail",
    "test_classifier_binary",           # sklearn surface
    "test_cv",                          # cv + callbacks
    "test_early_stopping",
    "test_shap_contribs_sum",           # SHAP
    "test_virtual_file_scheme_hook",    # IO seams
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        base = item.name.split("[")[0]
        if base in _FAST_TESTS and "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)
            matched.add(base)
    missing = _FAST_TESTS - matched
    # renames must not silently shrink the smoke tier.  Only checkable
    # when the whole suite was collected, so key off the invocation
    # (bare `pytest` / `pytest tests/`), not an item-count heuristic —
    # --ignore/-k subsets and file runs must not trip it.
    whole_suite = not config.getoption("ignore", None) \
        and not config.getoption("ignore_glob", None) \
        and not config.getoption("deselect", None) \
        and not config.getoption("keyword", "") \
        and all(os.path.isdir(a.split("::")[0]) for a in config.args)
    if missing and whole_suite:
        raise pytest.UsageError(
            f"fast-tier tests not collected: {missing}")
