"""Test harness: force a CPU-only 8-device virtual mesh.

Distributed learners are exercised on host-simulated devices (the
reference has no multi-node CI at all — SURVEY §4; this is the
deterministic multi-host substitute).  The TPU plugin environment may
override JAX_PLATFORMS via a config update at interpreter start, so we
set the config explicitly after import — tests must never touch (or
hang on) the real accelerator tunnel.
"""
import os

# LGBM_TPU_ONCHIP=1 runs the suite against the real chip (for
# tests/test_tpu_onchip.py's Mosaic-numerics parity checks)
_ONCHIP = os.environ.get("LGBM_TPU_ONCHIP") == "1"

if not _ONCHIP:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _ONCHIP:
    jax.config.update("jax_platforms", "cpu")
# persistent compile cache: every TreeGrower instance re-jits its tree
# function, so without this the suite recompiles identical shapes
# dozens of times (round-1 suite exceeded 25 min; compiles dominated)
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..",
                               ".jax_cache_cpu"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
