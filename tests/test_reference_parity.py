"""True interop tests against the ACTUAL reference binary.

The reference CLI (v2.1.1) is built CPU-only into .refbuild/ (run
``sh tests/build_reference.sh`` once per checkout — the binary is not
committed).  These tests convert "claimed-compatible" into "proven":
  * a model file produced by the reference binary loads through
    ``Booster(model_file=...)`` and predicts identically to the
    reference's own ``task=predict`` output (5-decimal standard of the
    reference's tests/python_package_test/test_consistency.py:40-63);
  * a model file produced by THIS framework is accepted by the
    reference binary and predicts identically there.

Skipped when the binary is absent (fresh clone): build it with
``sh tests/build_reference.sh``.
"""
import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

REF_BIN = os.path.join(os.path.dirname(__file__), "..", ".refbuild",
                       "lightgbm")
REF_EXAMPLES = "/root/reference/examples"

pytestmark = [pytest.mark.slow, pytest.mark.skipif(
    not os.path.exists(REF_BIN),
    reason="reference binary not built — run: sh tests/build_reference.sh")]


def _run_ref(cwd, *args):
    r = subprocess.run([os.path.abspath(REF_BIN)] + list(args),
                       cwd=cwd, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    return r


def _load_tsv(path):
    raw = np.loadtxt(path)
    return raw[:, 1:], raw[:, 0]


@pytest.fixture(scope="module")
def binary_data():
    X, y = _load_tsv(f"{REF_EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load_tsv(f"{REF_EXAMPLES}/binary_classification/binary.test")
    return X, y, Xt, yt


@pytest.fixture(scope="module")
def ref_binary_model(tmp_path_factory):
    """Reference-CLI-trained binary model (shared across tests)."""
    d = tmp_path_factory.mktemp("refbin")
    model = d / "ref_model.txt"
    _run_ref(d, "task=train", "objective=binary",
             f"data={REF_EXAMPLES}/binary_classification/binary.train",
             "num_trees=20", "num_leaves=31", "min_data_in_leaf=20",
             "learning_rate=0.1", "verbosity=-1",
             f"output_model={model}")
    return model


@pytest.fixture(scope="module")
def our_binary_model(binary_data):
    """Our trained binary model (shared; same config as the
    reference fixture)."""
    X, y, _, _ = binary_data
    return lgb.train({"objective": "binary", "num_leaves": 31,
                      "min_data_in_leaf": 20, "learning_rate": 0.1,
                      "verbose": -1}, lgb.Dataset(X, label=y), 20,
                     verbose_eval=False)


def test_reference_model_loads_and_predicts_identically(tmp_path,
                                                        binary_data,
                                                        ref_binary_model):
    """Reference-trained model -> our Booster: predictions match the
    reference's own predict output to 5 decimals."""
    _, _, Xt, _ = binary_data
    model = ref_binary_model
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/binary_classification/binary.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)

    bst = lgb.Booster(model_file=str(model))
    ours = bst.predict(Xt)
    np.testing.assert_allclose(ours, ref_pred, atol=1e-5)


def test_reference_regression_model_interop(tmp_path):
    model = tmp_path / "ref_model.txt"
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=train", "objective=regression",
             f"data={REF_EXAMPLES}/regression/regression.train",
             "num_trees=15", "num_leaves=31", "verbosity=-1",
             f"output_model={model}")
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/regression/regression.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    Xt, _ = _load_tsv(f"{REF_EXAMPLES}/regression/regression.test")
    bst = lgb.Booster(model_file=str(model))
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-5)


def test_our_model_accepted_by_reference_binary(tmp_path, binary_data,
                                                our_binary_model):
    """Our saved model -> reference binary predict: the reference
    parses it and produces our predictions to 5 decimals."""
    X, y, Xt, _ = binary_data
    bst = our_binary_model
    model = tmp_path / "our_model.txt"
    bst.save_model(str(model))
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/binary_classification/binary.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-5)


def test_reference_lambdarank_model_interop(tmp_path):
    """Ranking: reference-trained lambdarank model -> our Booster
    predicts identically; our lambdarank model -> reference binary
    predicts identically (query side files resolved by both)."""
    model = tmp_path / "ref_model.txt"
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=train", "objective=lambdarank",
             f"data={REF_EXAMPLES}/lambdarank/rank.train",
             "num_trees=15", "num_leaves=31", "min_data_in_leaf=20",
             "verbosity=-1", f"output_model={model}")
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/lambdarank/rank.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    from lightgbm_tpu.data_loader import _load_libsvm
    Xt, _ = _load_libsvm(f"{REF_EXAMPLES}/lambdarank/rank.test")
    bst = lgb.Booster(model_file=str(model))
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-5)

    # ours -> reference
    X, y = _load_libsvm(f"{REF_EXAMPLES}/lambdarank/rank.train")
    group = np.loadtxt(
        f"{REF_EXAMPLES}/lambdarank/rank.train.query").astype(int)
    ours = lgb.train({"objective": "lambdarank", "num_leaves": 31,
                      "min_data_in_leaf": 20, "verbose": -1},
                     lgb.Dataset(X, label=y, group=group), 15,
                     verbose_eval=False)
    our_model = tmp_path / "our_model.txt"
    ours.save_model(str(our_model))
    our_pred_out = tmp_path / "our_ref_pred.txt"
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/lambdarank/rank.test",
             f"input_model={our_model}",
             f"output_result={our_pred_out}")
    np.testing.assert_allclose(ours.predict(Xt),
                               np.loadtxt(our_pred_out), atol=1e-5)


def test_reference_multiclass_model_interop(tmp_path):
    """Softmax: the reference's 5-class example model loads and the
    (n, 5) probability matrix matches its own predict output."""
    model = tmp_path / "ref_model.txt"
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=train", "objective=multiclass",
             "num_class=5",
             f"data={REF_EXAMPLES}/multiclass_classification/multiclass.train",
             "num_trees=10", "num_leaves=31", "verbosity=-1",
             f"output_model={model}")
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/multiclass_classification/multiclass.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    Xt, _ = _load_tsv(
        f"{REF_EXAMPLES}/multiclass_classification/multiclass.test")
    bst = lgb.Booster(model_file=str(model))
    ours = bst.predict(Xt)
    assert ours.shape == ref_pred.shape == (Xt.shape[0], 5)
    np.testing.assert_allclose(ours, ref_pred, atol=1e-5)


def test_training_accuracy_parity_binary(binary_data, ref_binary_model,
                                         our_binary_model):
    """Same data + config trained by both implementations: held-out
    logloss within 2% relative — the algorithmic-parity gate (exact
    tree equality is not expected: float summation order differs)."""
    _, _, Xt, yt = binary_data
    ref_bst = lgb.Booster(model_file=str(ref_binary_model))
    ref_p = np.clip(ref_bst.predict(Xt), 1e-7, 1 - 1e-7)
    ref_ll = -np.mean(yt * np.log(ref_p) + (1 - yt) * np.log(1 - ref_p))

    our_p = np.clip(our_binary_model.predict(Xt), 1e-7, 1 - 1e-7)
    our_ll = -np.mean(yt * np.log(our_p) + (1 - yt) * np.log(1 - our_p))
    assert our_ll <= ref_ll * 1.02, (our_ll, ref_ll)


def test_bench_config_255_leaf_parity(tmp_path):
    """The bench config (num_leaves=255, max_bin=63) proven against the
    reference binary at scale (round-3 verdict weak #3): model exchange
    must hold to 1e-5 in BOTH directions for deep 255-leaf trees, the
    frontier budget (default 126 splits/round) must not change the grown
    trees under gain exhaustion (any narrower budget yields
    bit-identical predictions), and when the leaf cap binds the width
    effect and the reference gap are bounded by held-out logloss."""
    rng = np.random.RandomState(7)
    n, f = 30_000, 28
    X = rng.randn(n, f)
    w = rng.randn(f) * (rng.rand(f) > 0.3)
    y = (X @ w + 0.5 * np.sin(3 * X[:, 0]) * X[:, 1]
         + rng.logistic(size=n) > 0).astype(float)
    Xt, yt = X[:5000], y[:5000]

    train_csv = tmp_path / "train.csv"
    test_csv = tmp_path / "test.csv"
    np.savetxt(train_csv, np.column_stack([y, X]), fmt="%.8g",
               delimiter=",")
    np.savetxt(test_csv, np.column_stack([yt, Xt]), fmt="%.8g",
               delimiter=",")

    cfg = dict(objective="binary", num_leaves=255, max_bin=63,
               learning_rate=0.1, min_data_in_leaf=20)
    ref_model = tmp_path / "ref_model.txt"
    _run_ref(tmp_path, "task=train", f"data={train_csv}",
             "num_trees=4", "verbosity=-1",
             f"output_model={ref_model}",
             *[f"{k}={v}" for k, v in cfg.items()])
    ref_pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=predict", f"data={test_csv}",
             f"input_model={ref_model}", f"output_result={ref_pred_out}")
    ref_pred = np.loadtxt(ref_pred_out)

    # direction 1: the reference's deep 255-leaf model loads here and
    # predicts identically
    ref_bst = lgb.Booster(model_file=str(ref_model))
    assert max(t["num_leaves"]
               for t in ref_bst.dump_model()["tree_info"]) > 126, \
        "reference trees too shallow to exercise the 255-leaf regime"
    np.testing.assert_allclose(ref_bst.predict(Xt), ref_pred, atol=1e-5)

    # direction 2: our 255-leaf model is accepted by the reference
    # binary and predicts identically there
    ours = lgb.train(dict(cfg, verbose=-1), lgb.Dataset(X, label=y), 4,
                     verbose_eval=False)
    assert max(t["num_leaves"]
               for t in ours.dump_model()["tree_info"]) > 126, \
        "our trees too shallow to exercise the 255-leaf regime"
    our_model = tmp_path / "our_model.txt"
    ours.save_model(str(our_model))
    our_pred_out = tmp_path / "our_pred.txt"
    _run_ref(tmp_path, "task=predict", f"data={test_csv}",
             f"input_model={our_model}", f"output_result={our_pred_out}")
    np.testing.assert_allclose(ours.predict(Xt),
                               np.loadtxt(our_pred_out), atol=1e-5)

    # frontier-budget semantics.  When growth ends by GAIN EXHAUSTION
    # (min_data stops splitting before the 255-leaf cap), the frontier
    # width must be invisible: batched rounds split exactly the set of
    # positive-gain leaves sequential best-first would, so any width
    # gives bit-identical trees.
    exh = dict(cfg, min_data_in_leaf=1500, verbose=-1)
    wide_e = lgb.train(exh, lgb.Dataset(X, label=y), 4,
                       verbose_eval=False)
    narrow_e = lgb.train(dict(exh, frontier_width=32),
                         lgb.Dataset(X, label=y), 4, verbose_eval=False)
    assert max(t["num_leaves"]
               for t in wide_e.dump_model()["tree_info"]) < 255
    np.testing.assert_array_equal(wide_e.predict(Xt),
                                  narrow_e.predict(Xt))

    # When the 255-leaf CAP binds, batched selection near the cap is a
    # DOCUMENTED deviation from one-split-at-a-time best-first (the
    # exact order would need 254 histogram passes per tree —
    # learner/grower.py module doc): the last few split choices can
    # differ between widths, but the model quality must not — bound
    # the width effect and the reference gap by held-out logloss.
    ll = lambda p: -np.mean(yt * np.log(p) + (1 - yt) * np.log(1 - p))
    narrow = lgb.train(dict(cfg, verbose=-1, frontier_width=64),
                       lgb.Dataset(X, label=y), 4, verbose_eval=False)
    ll_wide = ll(np.clip(ours.predict(Xt), 1e-7, 1 - 1e-7))
    ll_narrow = ll(np.clip(narrow.predict(Xt), 1e-7, 1 - 1e-7))
    assert abs(ll_wide - ll_narrow) <= 0.01 * max(ll_wide, ll_narrow), \
        (ll_wide, ll_narrow)

    # algorithmic parity: held-out logloss within 2% of the reference
    ref_ll = ll(np.clip(ref_pred, 1e-7, 1 - 1e-7))
    assert ll_wide <= ref_ll * 1.02, (ll_wide, ref_ll)
