"""True interop tests against the ACTUAL reference binary.

The reference CLI (v2.1.1) is built CPU-only into .refbuild/ (run
``sh tests/build_reference.sh`` once per checkout — the binary is not
committed).  These tests convert "claimed-compatible" into "proven":
  * a model file produced by the reference binary loads through
    ``Booster(model_file=...)`` and predicts identically to the
    reference's own ``task=predict`` output (5-decimal standard of the
    reference's tests/python_package_test/test_consistency.py:40-63);
  * a model file produced by THIS framework is accepted by the
    reference binary and predicts identically there.

Skipped when the binary is absent (fresh clone): build it with
``sh tests/build_reference.sh``.
"""
import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

REF_BIN = os.path.join(os.path.dirname(__file__), "..", ".refbuild",
                       "lightgbm")
REF_EXAMPLES = "/root/reference/examples"

pytestmark = [pytest.mark.slow, pytest.mark.skipif(
    not os.path.exists(REF_BIN),
    reason="reference binary not built — run: sh tests/build_reference.sh")]


def _run_ref(cwd, *args):
    r = subprocess.run([os.path.abspath(REF_BIN)] + list(args),
                       cwd=cwd, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    return r


def _load_tsv(path):
    raw = np.loadtxt(path)
    return raw[:, 1:], raw[:, 0]


@pytest.fixture(scope="module")
def binary_data():
    X, y = _load_tsv(f"{REF_EXAMPLES}/binary_classification/binary.train")
    Xt, yt = _load_tsv(f"{REF_EXAMPLES}/binary_classification/binary.test")
    return X, y, Xt, yt


@pytest.fixture(scope="module")
def ref_binary_model(tmp_path_factory):
    """Reference-CLI-trained binary model (shared across tests)."""
    d = tmp_path_factory.mktemp("refbin")
    model = d / "ref_model.txt"
    _run_ref(d, "task=train", "objective=binary",
             f"data={REF_EXAMPLES}/binary_classification/binary.train",
             "num_trees=20", "num_leaves=31", "min_data_in_leaf=20",
             "learning_rate=0.1", "verbosity=-1",
             f"output_model={model}")
    return model


@pytest.fixture(scope="module")
def our_binary_model(binary_data):
    """Our trained binary model (shared; same config as the
    reference fixture)."""
    X, y, _, _ = binary_data
    return lgb.train({"objective": "binary", "num_leaves": 31,
                      "min_data_in_leaf": 20, "learning_rate": 0.1,
                      "verbose": -1}, lgb.Dataset(X, label=y), 20,
                     verbose_eval=False)


def test_reference_model_loads_and_predicts_identically(tmp_path,
                                                        binary_data,
                                                        ref_binary_model):
    """Reference-trained model -> our Booster: predictions match the
    reference's own predict output to 5 decimals."""
    _, _, Xt, _ = binary_data
    model = ref_binary_model
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/binary_classification/binary.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)

    bst = lgb.Booster(model_file=str(model))
    ours = bst.predict(Xt)
    np.testing.assert_allclose(ours, ref_pred, atol=1e-5)


def test_reference_regression_model_interop(tmp_path):
    model = tmp_path / "ref_model.txt"
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=train", "objective=regression",
             f"data={REF_EXAMPLES}/regression/regression.train",
             "num_trees=15", "num_leaves=31", "verbosity=-1",
             f"output_model={model}")
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/regression/regression.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    Xt, _ = _load_tsv(f"{REF_EXAMPLES}/regression/regression.test")
    bst = lgb.Booster(model_file=str(model))
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-5)


def test_our_model_accepted_by_reference_binary(tmp_path, binary_data,
                                                our_binary_model):
    """Our saved model -> reference binary predict: the reference
    parses it and produces our predictions to 5 decimals."""
    X, y, Xt, _ = binary_data
    bst = our_binary_model
    model = tmp_path / "our_model.txt"
    bst.save_model(str(model))
    pred_out = tmp_path / "ref_pred.txt"
    _run_ref(tmp_path, "task=predict",
             f"data={REF_EXAMPLES}/binary_classification/binary.test",
             f"input_model={model}", f"output_result={pred_out}")
    ref_pred = np.loadtxt(pred_out)
    np.testing.assert_allclose(bst.predict(Xt), ref_pred, atol=1e-5)


def test_training_accuracy_parity_binary(binary_data, ref_binary_model,
                                         our_binary_model):
    """Same data + config trained by both implementations: held-out
    logloss within 2% relative — the algorithmic-parity gate (exact
    tree equality is not expected: float summation order differs)."""
    _, _, Xt, yt = binary_data
    ref_bst = lgb.Booster(model_file=str(ref_binary_model))
    ref_p = np.clip(ref_bst.predict(Xt), 1e-7, 1 - 1e-7)
    ref_ll = -np.mean(yt * np.log(ref_p) + (1 - yt) * np.log(1 - ref_p))

    our_p = np.clip(our_binary_model.predict(Xt), 1e-7, 1 - 1e-7)
    our_ll = -np.mean(yt * np.log(our_p) + (1 - yt) * np.log(1 - our_p))
    assert our_ll <= ref_ll * 1.02, (our_ll, ref_ll)
