"""Chaos harness + deadline watchdog + degraded-mode continuation
(docs/RELIABILITY.md, "Chaos testing" / "Deadline watchdog").

Acceptance pins for the round-19 robustness layer:

- seeded chaos plans are deterministic (same seed -> same draw),
  glob-filtered, and parse through the ``chaos:<seed>:<n>[:glob]``
  grammar; ``hang:<ms>`` / ``slow:<ms>`` actions block/delay seams;
- an injected ``hang`` at a COLLECTIVE seam and at a DISPATCH seam is
  caught by the watchdog within its configured deadline, produces an
  all-thread stack flight dump naming the seam, and surfaces as a
  classified ``StallError`` that rides the existing retry machinery;
- exhausted retries and stalls leave a metric trail
  (``retry_exhausted_total`` / ``stalls_total``);
- degraded-mode sharded construction (``sharded_allow_degraded=on``,
  one participant dead or hung past deadline) completes with trees
  BYTE-IDENTICAL to a from-scratch run on the surviving world, while
  the default-off path still fails fast;
- the invariant registry catches torn artifacts, diverging ledgers,
  silent serving corruption and quiet partial successes;
- a torn/bit-flipped checkpoint at every container boundary is
  rejected loudly and ``resume=auto`` falls back to the next-newest
  valid file, never a partial restore;
- ``task=serve`` drains and exits 0 on a REAL SIGTERM.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.reliability import chaos
from lightgbm_tpu.reliability import checkpoint as ck
from lightgbm_tpu.reliability import invariants as inv
from lightgbm_tpu.reliability import watchdog as wd
from lightgbm_tpu.reliability.faults import (FAULTS, FaultInjected,
                                             SEAMS, parse_plan)
from lightgbm_tpu.reliability.retry import (RetryPolicy, is_transient,
                                            retry_call)
from lightgbm_tpu.reliability.watchdog import (WATCHDOG, StallError,
                                               run_with_deadline)
from lightgbm_tpu.telemetry import TELEMETRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_harness():
    """No armed plan, no armed deadline, clean telemetry — before AND
    after every test (all three are process globals)."""
    FAULTS.reset()
    for p in wd.PHASES:
        wd.set_deadline(p, 0.0)
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    yield
    FAULTS.reset()
    for p in wd.PHASES:
        wd.set_deadline(p, 0.0)
    TELEMETRY.flight.disarm()
    TELEMETRY.configure("off")
    TELEMETRY.reset()


def _data(n=240, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.25 * rng.randn(n) > 0).astype(float)
    return X, y


BASE = dict(objective="binary", num_leaves=7, max_bin=31, verbose=-1,
            min_data_in_leaf=5, dispatch_chunk=4, retry_backoff_s=0.0)


# ---------------------------------------------------------------------------
# chaos scheduler: deterministic draws, glob filter, grammar
# ---------------------------------------------------------------------------
def test_chaos_draw_deterministic_and_replayable():
    a = chaos.chaos_entries(7, 5)
    b = chaos.chaos_entries(7, 5)
    assert a == b, "same seed must draw the identical plan"
    assert chaos.chaos_entries(8, 5) != a
    for seam, nth, action in a:
        assert seam in SEAMS
        assert nth >= 1
    assert chaos.chaos_spec(7, 5) == ";".join(
        f"{s}:{n}:{x}" for s, n, x in a)


def test_chaos_glob_filter_and_action_set():
    assert chaos.chaos_seams("gbdt.*") == ["gbdt.train_chunk",
                                           "gbdt.train_one_iter"]
    assert set(chaos.chaos_seams("gbdt.*,checkpoint.io")) == {
        "gbdt.train_chunk", "gbdt.train_one_iter", "checkpoint.io"}
    with pytest.raises(ValueError, match="matches no registered"):
        chaos.chaos_seams("nope.*")
    drawn = chaos.chaos_entries(3, 20, "predict.dispatch",
                                actions=("slow",), max_nth=20,
                                slow_ms=(5, 9))
    assert len({(s, n) for s, n, _ in drawn}) == 20, \
        "draws must never shadow each other at one (seam, nth)"
    for seam, _nth, action in drawn:
        assert seam == "predict.dispatch"
        assert action.startswith("slow:")
        assert 5 <= int(action.split(":")[1]) <= 9
    # an overdrawn plan (more faults than distinct pairs) errors
    # loudly instead of silently injecting fewer than it claims
    with pytest.raises(ValueError, match="distinct"):
        chaos.chaos_entries(3, 20, "predict.dispatch")


def test_chaos_grammar_parses_and_rejects():
    entries = parse_plan("chaos:11:4:gbdt.*")
    assert len(entries) == 4
    assert all(e.seam.startswith("gbdt.") for e in entries)
    # composes with scripted entries
    mixed = parse_plan("chaos:11:2;predict.dispatch:1:oom")
    assert len(mixed) == 3
    with pytest.raises(ValueError, match="seed"):
        parse_plan("chaos:x:4")
    with pytest.raises(ValueError, match="count"):
        parse_plan("chaos:3:0")
    with pytest.raises(ValueError, match="matches no registered"):
        parse_plan("chaos:3:2:bogus.*")


def test_hang_slow_actions_parse_and_fire():
    e = parse_plan("gbdt.train_chunk:2:hang:400;"
                   "predict.dispatch:1:slow:20:x3")
    assert (e[0].action, e[0].duration_ms) == ("hang", 400)
    assert (e[1].action, e[1].duration_ms, e[1].count) == \
        ("slow", 20, 3)
    with pytest.raises(ValueError, match="millisecond"):
        parse_plan("gbdt.train_chunk:1:hang")
    with pytest.raises(ValueError, match="millisecond"):
        parse_plan("gbdt.train_chunk:1:slow:abc")
    # slow: delays, then proceeds
    FAULTS.configure("predict.dispatch:1:slow:40")
    t0 = time.perf_counter()
    FAULTS.fault_point("predict.dispatch")
    assert time.perf_counter() - t0 >= 0.03
    # hang: blocks, then errors (the op never completed)
    FAULTS.configure("predict.dispatch:1:hang:40")
    t0 = time.perf_counter()
    with pytest.raises(FaultInjected, match="hang released"):
        FAULTS.fault_point("predict.dispatch")
    assert time.perf_counter() - t0 >= 0.03


# ---------------------------------------------------------------------------
# watchdog core
# ---------------------------------------------------------------------------
def test_run_with_deadline_semantics(tmp_path):
    assert run_with_deadline(lambda a, b: a + b, 0.0, "p", "s",
                             1, 2) == 3       # disarmed = inline
    assert run_with_deadline(lambda: "ok", 5.0, "p", "s") == "ok"
    with pytest.raises(KeyError):              # exceptions relay
        run_with_deadline(lambda: {}["x"], 5.0, "p", "s")
    TELEMETRY.flight.arm(str(tmp_path / "flight"))
    t0 = time.perf_counter()
    with pytest.raises(StallError, match="deadline exceeded"):
        run_with_deadline(lambda: time.sleep(1.0), 0.1,
                          "unit_phase", "predict.dispatch")
    assert time.perf_counter() - t0 < 0.8, \
        "the stall must surface AT the deadline, not after the hang"
    assert TELEMETRY.counters().get("stalls_total") == 1
    dump = json.load(open(TELEMETRY.flight.dumps[-1]))
    assert dump["reason"] == "stall"
    assert dump["seam"] == "predict.dispatch"
    assert dump["stacks"], "the dump must carry all-thread stacks"
    assert any("time.sleep" in ln or "sleep" in ln
               for frames in dump["stacks"].values()
               for ln in frames), "the stalled frame must be visible"
    # the classification contract: StallError rides the retry
    # machinery as a transient error
    assert is_transient(StallError("p", "s", 0.1))


def test_watchdog_monitor_watch_and_cancel(tmp_path):
    TELEMETRY.flight.arm(str(tmp_path / "flight"))
    token = WATCHDOG.watch("unit_watch", 0.08, seam="continuous.cycle")
    deadline = time.perf_counter() + 5.0
    while not TELEMETRY.flight.dumps:
        assert time.perf_counter() < deadline, "watch never fired"
        time.sleep(0.02)
    assert TELEMETRY.counters().get("stalls_total") == 1
    dump = json.load(open(TELEMETRY.flight.dumps[-1]))
    assert dump["phase"] == "unit_watch"
    assert dump["seam"] == "continuous.cycle"
    # a cancelled token must never fire
    TELEMETRY.reset()
    token = WATCHDOG.watch("unit_watch2", 0.08)
    WATCHDOG.cancel(token)
    time.sleep(0.2)
    assert not TELEMETRY.counters().get("stalls_total")


# ---------------------------------------------------------------------------
# acceptance: hang at a dispatch seam / a collective seam
# ---------------------------------------------------------------------------
def test_dispatch_hang_caught_by_watchdog(tmp_path):
    TELEMETRY.flight.arm(str(tmp_path / "flight"))
    X, y = _data()
    # the hang fires at the FIRST dispatch call, BEFORE the enqueue
    # traces/compiles anything — so a 1 s deadline under a 6 s hang
    # pins 'caught within the configured deadline' without cold
    # compile noise
    params = dict(BASE, watchdog_dispatch_s=1.0, dispatch_retries=0)
    FAULTS.configure("gbdt.train_chunk:1:hang:6000")
    t0 = time.perf_counter()
    with pytest.raises(StallError, match="gbdt.train_chunk"):
        lgb.train(params, lgb.Dataset(X, label=y), 4,
                  verbose_eval=False)
    assert time.perf_counter() - t0 < 5.0, \
        "caught at the deadline, not at hang release"
    assert TELEMETRY.counters().get("stalls_total", 0) >= 1
    # the flight trail: a stall dump naming the seam, with stacks,
    # AND the retry-exhaustion dump (dispatch_retries=0)
    dumps = [json.load(open(p)) for p in TELEMETRY.flight.dumps]
    stall = [d for d in dumps if d["reason"] == "stall"]
    assert stall and stall[-1]["seam"] == "gbdt.train_chunk"
    assert stall[-1]["stacks"]
    assert any(d["reason"] == "retry_exhausted" for d in dumps)
    assert TELEMETRY.counters().get("retry_exhausted_total") == 1


def test_dispatch_stall_retried_to_success():
    """StallError is TRANSIENT: with retries left, a one-shot hang is
    absorbed and training completes — the 'through the existing retry
    machinery' half of the acceptance criterion."""
    X, y = _data()
    # deadline sized ABOVE the retry attempt's trace+compile wall
    # (the docs' sizing rule) but under the 20 s hang
    params = dict(BASE, watchdog_dispatch_s=4.0, dispatch_retries=2)
    FAULTS.configure("gbdt.train_chunk:1:hang:15000")
    bst = lgb.train(params, lgb.Dataset(X, label=y), 4,
                    verbose_eval=False)
    assert bst.num_trees() == 4
    c = TELEMETRY.counters()
    assert c.get("stalls_total", 0) >= 1
    assert c.get("retries", 0) >= 1
    assert not c.get("retry_exhausted_total")


def test_collective_hang_caught_by_watchdog(tmp_path):
    TELEMETRY.flight.arm(str(tmp_path / "flight"))
    wd.set_deadline("collective", 0.15)
    from lightgbm_tpu.parallel.distributed import _allgather
    FAULTS.configure("collectives.allgather:1:hang:2000")
    t0 = time.perf_counter()
    with pytest.raises(StallError, match="collectives.allgather"):
        _allgather(np.arange(4.0))
    assert time.perf_counter() - t0 < 1.5
    dump = json.load(open(TELEMETRY.flight.dumps[-1]))
    assert dump["seam"] == "collectives.allgather"
    assert dump["stacks"]
    FAULTS.reset()
    out = _allgather(np.arange(4.0))   # the plane survives
    assert out.reshape(-1).shape[0] >= 4


def test_host_collective_backend_carries_seam_and_deadline():
    from lightgbm_tpu.parallel.collectives import HostCollectives
    hc = HostCollectives(shards=2)
    FAULTS.configure("collectives.allgather:1:ConnectionError")
    with pytest.raises(ConnectionError, match="injected at seam"):
        hc.simulate_allgather([np.arange(2.0), np.arange(2.0)])
    FAULTS.reset()
    wd.set_deadline("collective", 0.1)
    FAULTS.configure("collectives.allgather:1:hang:1500")
    with pytest.raises(StallError):
        hc.simulate_allgather([np.arange(2.0), np.arange(2.0)])


def test_checkpoint_io_hang_caught(tmp_path):
    wd.set_deadline("checkpoint", 0.1)
    FAULTS.configure("checkpoint.io:1:hang:1500")
    with pytest.raises(StallError, match="checkpoint.io"):
        ck.atomic_write_text(str(tmp_path / "x.txt"), "hello")
    FAULTS.reset()
    ck.atomic_write_text(str(tmp_path / "x.txt"), "hello")
    assert open(tmp_path / "x.txt").read() == "hello"


def test_resume_scan_never_falls_back_past_a_stalled_read(tmp_path):
    """A hung checkpoint READ must surface as StallError, NOT convert
    to CheckpointError: find_resume swallows CheckpointError to fall
    back to older files, and a stalled filesystem must not let it
    silently resume from stale state it 'fell back' to without ever
    reading the newer checkpoint."""
    prefix = str(tmp_path / "m.ckpt")
    fp = "a" * 64
    ck.save_checkpoint(ck.checkpoint_file(prefix, 2), {"it": 2}, fp)
    ck.save_checkpoint(ck.checkpoint_file(prefix, 4), {"it": 4}, fp)
    wd.set_deadline("checkpoint", 0.1)
    # the scan's FIRST read (the newest file, iteration 4) hangs
    FAULTS.configure("checkpoint.io:1:hang:1500")
    with pytest.raises(StallError):
        ck.find_resume(prefix, fp)
    FAULTS.reset()
    assert ck.find_resume(prefix, fp)[0] == 4


def test_train_one_iter_seam_fires_on_unchunked_path():
    """2 iterations under dispatch_chunk=4 take the per-iteration
    path — the gbdt.train_one_iter seam must be live there."""
    X, y = _data()
    FAULTS.configure("gbdt.train_one_iter:1:slow:20")
    bst = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 2,
                    verbose_eval=False)
    assert bst.num_trees() == 2
    assert FAULTS.call_count("gbdt.train_one_iter") == 2
    assert TELEMETRY.counters().get("faults_injected") == 1


def test_distributed_init_seam_fails_loud_without_retry():
    """A non-transient error at the rendezvous seam propagates
    immediately (no retry burn) — and never reaches the real
    jax.distributed.initialize on this single-process backend."""
    from lightgbm_tpu.parallel import distributed
    FAULTS.configure("distributed.init:1:ValueError")
    with pytest.raises(ValueError, match="injected at seam"):
        distributed.initialize()
    assert not TELEMETRY.counters().get("retries")


# ---------------------------------------------------------------------------
# serving: stall classification
# ---------------------------------------------------------------------------
def test_batcher_stall_classified_and_counted():
    from lightgbm_tpu.serving.batcher import MicroBatcher, _Request
    cfg = Config.from_params({"verbose": -1, "watchdog_serve_s": 0.1})
    mb = MicroBatcher(lambda x: (time.sleep(1.0), x.sum(1))[1],
                      cfg, start=False)
    req = _Request(np.ones((2, 3)), 0.0)
    mb._run_batch([req])
    assert isinstance(req.error, StallError)
    c = TELEMETRY.counters()
    assert c.get("serve_stalls") == 1
    assert c.get("stalls_total") == 1
    assert c.get("serve_errors") == 1
    # unstalled dispatches still flow
    req2 = _Request(np.ones((2, 3)), 0.0)
    mb.predict = lambda x: x.sum(1)
    mb._run_batch([req2])
    assert req2.error is None and req2.result.shape == (2,)


def test_frontend_maps_stall_to_503():
    from lightgbm_tpu.serving.server import ServingFrontend

    class _Stub:
        def predict(self, name, rows):
            raise StallError("serve_dispatch", "predict.dispatch", 0.1)

        def names(self):
            return ["m"]

    fe = ServingFrontend(_Stub(), None)
    status, ctype, body, extra = fe._handle_predict(
        "POST", "/predict/m", b'{"rows": [[1.0, 2.0]]}', {})
    assert status == 503
    assert extra and "Retry-After" in extra
    payload = json.loads(body)
    assert payload.get("stall") is True


def test_retry_exhausted_counter_on_plain_transient():
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("connection reset")

    with pytest.raises(ConnectionError):
        retry_call(flaky, policy=RetryPolicy(max_retries=2,
                                             base_delay_s=0.0),
                   seam="unit", sleep=lambda s: None)
    assert len(calls) == 3
    c = TELEMETRY.counters()
    assert c.get("retries") == 2
    assert c.get("retry_exhausted_total") == 1


# ---------------------------------------------------------------------------
# degraded-mode sharded continuation
# ---------------------------------------------------------------------------
def _sharded_cfg(**over):
    return Config.from_params(dict(
        {"verbose": -1, "max_bin": 31, "min_data_in_leaf": 5}, **over))


def _survivor_slice(n, world, dead):
    from lightgbm_tpu.sharded.dataset import shard_row_ranges
    ranges = shard_row_ranges(n, world)
    return np.concatenate([np.arange(a, b)
                           for i, (a, b) in enumerate(ranges)
                           if i != dead])


def test_degraded_binfind_byte_identical_vs_surviving_world():
    from lightgbm_tpu.sharded.dataset import ShardedDataset
    X, y = _data(n=210)
    # default OFF: fail fast, unchanged semantics
    FAULTS.configure("sharded.binfind:2:RuntimeError")
    with pytest.raises(RuntimeError, match="injected at seam"):
        ShardedDataset.construct_sharded(X, label=y,
                                         config=_sharded_cfg(),
                                         num_shards=3)
    # degraded ON: participant 1 excluded, construction continues
    FAULTS.configure("sharded.binfind:2:RuntimeError")
    ds = ShardedDataset.construct_sharded(
        X, label=y, config=_sharded_cfg(sharded_allow_degraded=True),
        num_shards=3)
    FAULTS.reset()
    assert ds.world_size == 2
    keep = _survivor_slice(210, 3, dead=1)
    assert ds.num_data == len(keep)
    assert TELEMETRY.counters().get("sharded_degraded_exclusions") == 1
    ref = ShardedDataset.construct_sharded(
        X[keep], label=y[keep], config=_sharded_cfg(), num_shards=2)
    params = dict(BASE)
    m_deg = lgb.train(params, ds, 4, verbose_eval=False)
    m_ref = lgb.train(params, ref, 4, verbose_eval=False)
    assert m_deg.model_to_string() == m_ref.model_to_string(), \
        "degraded trees must be byte-identical to a from-scratch " \
        "run on the surviving world"


def test_degraded_participant_hang_excluded_past_deadline():
    from lightgbm_tpu.sharded.dataset import ShardedDataset
    X, y = _data(n=180)
    cfg = _sharded_cfg(sharded_allow_degraded=True,
                       watchdog_collective_s=0.15)
    FAULTS.configure("sharded.binfind:2:hang:2500")
    t0 = time.perf_counter()
    ds = ShardedDataset.construct_sharded(X, label=y, config=cfg,
                                          num_shards=3)
    assert ds.world_size == 2
    assert time.perf_counter() - t0 < 2.0, \
        "the hung participant must be cut at the deadline"
    assert TELEMETRY.counters().get("stalls_total", 0) >= 1


def test_degraded_ingest_exclusion():
    from lightgbm_tpu.sharded.dataset import ShardedDataset
    X, y = _data(n=180)
    FAULTS.configure("sharded.ingest:2:OSError")
    with pytest.raises(OSError):
        ShardedDataset.construct_sharded(X, label=y,
                                         config=_sharded_cfg(),
                                         num_shards=3)
    FAULTS.configure("sharded.ingest:2:OSError")
    ds = ShardedDataset.construct_sharded(
        X, label=y, config=_sharded_cfg(sharded_allow_degraded=True),
        num_shards=3)
    assert ds.world_size == 2
    assert ds.num_data == len(_survivor_slice(180, 3, dead=1))


# ---------------------------------------------------------------------------
# invariant registry
# ---------------------------------------------------------------------------
def test_invariant_no_partial_artifacts(tmp_path):
    d = str(tmp_path)
    assert not inv.run_invariants(
        inv.ChaosContext(workdir=d))["no_partial_artifacts"]
    open(os.path.join(d, "ckpt.tmp-1234"), "w").write("torn")
    v = inv.run_invariants(
        inv.ChaosContext(workdir=d))["no_partial_artifacts"]
    assert v and "ckpt.tmp-1234" in v[0]


def test_invariant_resume_byte_identical(tmp_path):
    a, b = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    open(a, "w").write("model")
    open(b, "w").write("model")
    ctx = inv.ChaosContext(reference_model=a, final_model=b)
    assert not inv.run_invariants(ctx)["resume_byte_identical"]
    open(b, "w").write("model2")
    assert inv.run_invariants(ctx)["resume_byte_identical"]
    ctx2 = inv.ChaosContext(reference_model=a,
                            final_model=str(tmp_path / "gone.txt"))
    assert inv.run_invariants(ctx2)["resume_byte_identical"]


def test_invariant_ledger_converges(tmp_path):
    led = str(tmp_path / "ledger.json")
    good = {"schema": 1, "cycle": 2, "phase": "idle",
            "cycle_slices": [], "cycle_decision": None,
            "processed": [], "last_good": "model_base.txt",
            "published": [], "quarantined": []}
    open(led, "w").write(json.dumps(good))
    assert not inv.run_invariants(
        inv.ChaosContext(ledger_path=led))["ledger_converges"]
    open(led, "w").write("{torn json")
    assert inv.run_invariants(
        inv.ChaosContext(ledger_path=led))["ledger_converges"]
    open(led, "w").write(json.dumps(dict(good, phase="exploded")))
    v = inv.run_invariants(
        inv.ChaosContext(ledger_path=led))["ledger_converges"]
    assert v and "re-enterable" in v[0]


def test_invariant_serving_parity_and_loud_failure(tmp_path):
    ctx = inv.ChaosContext(served=np.array([1.0, 2.0]),
                           expected=np.array([1.0, 2.0]))
    assert not inv.run_invariants(ctx)["serving_parity"]
    ctx.served = np.array([1.0, 2.5])
    assert inv.run_invariants(ctx)["serving_parity"]
    # loud failure: work lost + rc 0 = violation; rc != 0 + a dump
    # naming the seam = holds
    silent = inv.ChaosContext(work_lost=True, exit_code=0)
    v = inv.run_invariants(silent)["loud_failure"]
    assert len(v) == 2          # silent exit AND no seam-naming dump
    dump = str(tmp_path / "x.flight.json")
    open(dump, "w").write(json.dumps({"seam": "gbdt.train_chunk"}))
    loud = inv.ChaosContext(work_lost=True, exit_code=-9,
                            flight_dumps=[dump])
    assert not inv.run_invariants(loud)["loud_failure"]
    with pytest.raises(ValueError, match="unknown invariant"):
        inv.run_invariants(loud, ["nope"])


# ---------------------------------------------------------------------------
# checkpoint torn-write fuzz (satellite)
# ---------------------------------------------------------------------------
def test_checkpoint_torn_write_fuzz(tmp_path):
    """Truncations and bit-flips at every container boundary (magic /
    schema / fingerprint / payload length / payload / trailing hash)
    must be rejected loudly, and resume=auto must fall back to the
    next-newest VALID checkpoint — never a silent partial restore."""
    prefix = str(tmp_path / "m.ckpt")
    fp = "f" * 64
    ck.save_checkpoint(ck.checkpoint_file(prefix, 2),
                       {"iteration": 2, "blob": b"x" * 256}, fp)
    newest = ck.checkpoint_file(prefix, 4)
    ck.save_checkpoint(newest, {"iteration": 4, "blob": b"y" * 256},
                       fp)
    pristine = open(newest, "rb").read()
    L = len(pristine)
    header = len(ck.MAGIC)                      # 10
    cases = []
    # truncations at: empty file, inside magic, inside schema, inside
    # the fingerprint, inside payload-length, inside the payload, and
    # inside the trailing hash
    for cut in (0, 5, header + 2, header + 8 + 30, header + 8 + 66,
                L - 40, L - 10):
        cases.append(("truncate@%d" % cut, pristine[:cut]))
    # single-bit flips at the same boundaries
    for flip in (2, header + 1, header + 4 + 1, header + 8 + 5,
                 header + 8 + 64 + 4, L - 40, L - 5):
        b = bytearray(pristine)
        b[flip] ^= 0x40
        cases.append(("bitflip@%d" % flip, bytes(b)))
    for name, blob in cases:
        with open(newest, "wb") as f:
            f.write(blob)
        with pytest.raises(ck.CheckpointError):
            ck.read_checkpoint(newest, fp)
        res = ck.find_resume(prefix, fp)
        assert res is not None, f"{name}: resume found nothing"
        assert res[0] == 2, \
            f"{name}: resume must fall back to iteration 2"
        assert res[1]["iteration"] == 2
    with open(newest, "wb") as f:
        f.write(pristine)                        # pristine again
    assert ck.find_resume(prefix, fp)[0] == 4


# ---------------------------------------------------------------------------
# binary-cache (v2/v3/v4) + shard-manifest torn-write fuzz (satellite)
# ---------------------------------------------------------------------------
def _cache_fuzz_cases(pristine: bytes, header_end: int,
                      footer_len: int):
    """Truncations and single-bit flips at every section boundary of
    a v2-family cache file: token, magic, header length, header blob,
    raw bin section, trailing footer."""
    from lightgbm_tpu import dataset_io
    L = len(pristine)
    tok = len(dataset_io.BINARY_TOKEN)
    bins_mid = (header_end + (L - footer_len)) // 2
    cases = []
    for cut in (0, tok // 2, tok + 3, tok + 8 + 4, header_end - 9,
                bins_mid, L - footer_len // 2):
        cases.append((f"truncate@{cut}", pristine[:cut]))
    for flip in (tok + 1, tok + 8 + 2, header_end - 17,
                 header_end + 1, bins_mid, L - 4):
        b = bytearray(pristine)
        b[flip % L] ^= 0x40
        cases.append((f"bitflip@{flip % L}", bytes(b)))
    # amputating EXACTLY the footer masquerades as a legacy pre-footer
    # file: the bins are intact there, so the only acceptable outcome
    # is a bit-identical (warned) load — covered by the caller's
    # "any successful load must match pristine" invariant
    cases.append((f"truncate@{L - footer_len}",
                  pristine[:L - footer_len]))
    return cases


@pytest.mark.parametrize("packing,version", [
    ("8bit", 2), ("4bit", 3), ("2bit", 4)])
def test_binary_cache_torn_write_fuzz(tmp_path, packing, version):
    """ISSUE 20 satellite: every v2-family cache version under torn
    writes.  Any truncation or bit flip at a section boundary must be
    rejected loudly, OR (when the mutation happens to leave the data
    bytes intact, e.g. amputating exactly the footer) load
    bit-identical — a silently-wrong load is the one outcome the
    trailing section digests exist to kill."""
    import pickle
    import struct

    from lightgbm_tpu import dataset_io
    rng = np.random.RandomState(7)
    X = rng.randn(300, 6)
    X[:, 2] = rng.randint(0, 2, 300)
    y = (X[:, 0] > 0).astype(float)
    params = {"objective": "binary", "verbose": -1,
              "bin_packing": packing}
    if packing == "4bit":
        params["max_bin"] = 15
    elif packing == "2bit":
        params["max_bin"] = 3
    cfg = Config.from_params(params)
    ds = lgb.Dataset(X, label=y).construct(cfg)
    path = str(tmp_path / "cache.bin")
    dataset_io.save_binary(ds, path)
    pristine = open(path, "rb").read()
    tok = len(dataset_io.BINARY_TOKEN) + len(dataset_io.MAGIC_V2)
    (blob_len,) = struct.unpack("<Q", pristine[tok:tok + 8])
    header_end = tok + 8 + blob_len
    hdr = pickle.loads(pristine[tok + 8:header_end])
    assert hdr["version"] == version, \
        "fuzz is not covering the cache version it claims to cover"
    assert pristine.endswith(
        dataset_io._FOOTER.pack(
            dataset_io._section_crc(pristine[tok + 8:header_end]),
            dataset_io._section_crc(
                pristine[header_end:len(pristine)
                         - dataset_io._FOOTER_LEN]))[-8:])
    ref_bins = np.asarray(ds.group_bins).copy()
    ref_label = np.asarray(ds.metadata.label).copy()
    for name, blob in _cache_fuzz_cases(pristine, header_end,
                                        dataset_io._FOOTER_LEN):
        with open(path, "wb") as f:
            f.write(blob)
        try:
            got = dataset_io.load_binary(path)
        except Exception:
            continue                     # loud rejection = correct
        np.testing.assert_array_equal(
            np.asarray(got.group_bins), ref_bins,
            err_msg=f"{name}: survived load differs from pristine")
        np.testing.assert_array_equal(
            np.asarray(got.metadata.label), ref_label,
            err_msg=f"{name}: survived load differs from pristine")
    with open(path, "wb") as f:
        f.write(pristine)                # pristine again
    dataset_io.load_binary(path)


def test_shard_manifest_torn_write_fuzz(tmp_path):
    """ISSUE 20 satellite: manifest.json under truncation + bit
    flips.  The self-digest (canonical-JSON crc32) must catch
    corruption that still parses as valid JSON; anything that loads
    anyway must be bit-identical to pristine (flips in the
    pretty-printing whitespace change no field)."""
    from lightgbm_tpu.sharded import (ShardedDataset, load_shard_cache,
                                      save_shard_cache)
    rng = np.random.RandomState(3)
    X = rng.randn(240, 5)
    y = (X[:, 0] > 0).astype(float)
    cfg = Config.from_params({"objective": "binary", "verbose": -1})
    sds = ShardedDataset.construct_sharded(X, label=y, config=cfg,
                                           num_shards=2)
    d = str(tmp_path / "cache")
    save_shard_cache(sds, d)
    mpath = os.path.join(d, "manifest.json")
    pristine = open(mpath, "rb").read()
    ref = load_shard_cache(d, expect_world_size=2)
    ref_bins = [np.asarray(b).copy() for b in ref.shard_bins]
    L = len(pristine)
    cases = [(f"truncate@{c}", pristine[:c])
             for c in (0, 7, L // 3, L - 2)]
    for flip in range(5, L - 1, max(1, L // 9)):
        b = bytearray(pristine)
        b[flip] ^= 0x20
        cases.append((f"bitflip@{flip}", bytes(b)))
    for name, blob in cases:
        with open(mpath, "wb") as f:
            f.write(blob)
        try:
            got = load_shard_cache(d, expect_world_size=2)
        except Exception:
            continue                     # loud rejection = correct
        for gb, rb in zip(got.shard_bins, ref_bins):
            np.testing.assert_array_equal(
                np.asarray(gb), rb,
                err_msg=f"{name}: survived load differs from pristine")
    with open(mpath, "wb") as f:
        f.write(pristine)                # pristine again
    load_shard_cache(d, expect_world_size=2)


# ---------------------------------------------------------------------------
# graceful SIGTERM drain (satellite; a REAL signal, a real subprocess)
# ---------------------------------------------------------------------------
def test_serve_sigterm_drains_and_exits_zero(tmp_path):
    X, y = _data()
    bst = lgb.train(dict(BASE), lgb.Dataset(X, label=y), 3,
                    verbose_eval=False)
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("LTPU_FAULT_PLAN", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "task=serve",
         f"input_model={model}", "serve_port=0", "verbose=1"],
        cwd=REPO, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True)
    try:
        lines = []
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stderr.readline()
            lines.append(line)
            if "serving model" in line:
                break
        else:
            pytest.fail("serve task never came up: "
                        + "".join(lines)[-2000:])
        proc.send_signal(signal.SIGTERM)
        _, rest = "", proc.communicate(timeout=60)[1] or ""
        stderr = "".join(lines) + rest
        assert proc.returncode == 0, \
            f"SIGTERM must exit 0, got {proc.returncode}: " \
            + stderr[-2000:]
        assert "SIGTERM: stopping admission and draining" in stderr
        assert "serving drained cleanly; exiting 0" in stderr
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)


# ---------------------------------------------------------------------------
# seam-coverage lint (satellite) — the two-way contract stays green
# ---------------------------------------------------------------------------
def test_seam_coverage_lint_green():
    run = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_seam_coverage.py")],
        capture_output=True, text=True, timeout=60)
    assert run.returncode == 0, run.stderr
    assert "all exercised and documented" in run.stdout
