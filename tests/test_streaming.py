"""Streaming / two-round construction (reference
src/io/dataset_loader.cpp:180-265, c_api.h:68-145 PushRows): the float
matrix never exists; peak memory = samples + one chunk + uint8 bins."""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Dataset as CoreDataset


def _write_csv(path, X, y):
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.8g")


@pytest.fixture(scope="module")
def csv_task(tmp_path_factory):
    rng = np.random.RandomState(0)
    X = rng.randn(3000, 8)
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    p = tmp_path_factory.mktemp("stream") / "train.csv"
    _write_csv(p, X, y)
    return str(p), X, y


def test_two_round_matches_in_ram_loading(csv_task):
    path, X, y = csv_task
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "bin_construct_sample_cnt": 5000}
    cfg1 = Config.from_params(params)
    core_ram = lgb.Dataset(path).construct(cfg1)
    cfg2 = Config.from_params(dict(params, two_round=True,
                                   streaming_chunk_rows=512))
    core_stream = lgb.Dataset(path).construct(cfg2)
    # identical sample => identical mappers => identical bin matrix
    np.testing.assert_array_equal(core_ram.group_bins,
                                  core_stream.group_bins)
    np.testing.assert_array_equal(core_ram.metadata.label,
                                  core_stream.metadata.label)


def test_two_round_trains(csv_task):
    path, X, y = csv_task
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "two_round": True, "streaming_chunk_rows": 700}
    bst = lgb.train(params, lgb.Dataset(path), 10, verbose_eval=False)
    acc = ((bst.predict(X) > 0.5) == y).mean()
    assert acc > 0.9


def test_two_round_binary_cache_roundtrip(csv_task, tmp_path):
    """Streamed construction -> binary cache -> reload: bit-equal."""
    from lightgbm_tpu.dataset_io import load_binary, save_binary
    path, _, _ = csv_task
    cfg = Config.from_params({"objective": "binary", "verbose": -1,
                              "two_round": True,
                              "streaming_chunk_rows": 512})
    core = lgb.Dataset(path).construct(cfg)
    bp = tmp_path / "train.bin"
    save_binary(core, str(bp))
    core2 = load_binary(str(bp))
    np.testing.assert_array_equal(core.group_bins, core2.group_bins)
    np.testing.assert_array_equal(core.metadata.label,
                                  core2.metadata.label)


def test_push_rows_dense_matches_matrix():
    rng = np.random.RandomState(1)
    X = rng.randn(1200, 6)
    X[rng.rand(1200, 6) < 0.3] = 0.0   # exercise EFB + default bins
    cfg = Config.from_params({"objective": "binary", "verbose": -1})
    ref = CoreDataset.from_matrix(X, label=np.zeros(1200), config=cfg)

    keep = [np.isnan(X[:, j]) | (np.abs(X[:, j]) > 1e-35)
            for j in range(6)]
    vals = [X[:, j][keep[j]] for j in range(6)]
    rows = [np.nonzero(keep[j])[0] for j in range(6)]
    ds = CoreDataset.from_sampled_columns(vals, rows, 1200, 1200,
                                          config=cfg)
    for s in range(0, 1200, 300):
        ds.push_rows(X[s:s + 300], s)
    ds.finish_load()
    np.testing.assert_array_equal(ds.group_bins, ref.group_bins)


def test_push_rows_csr_matches_dense_push():
    sp = pytest.importorskip("scipy.sparse")
    rng = np.random.RandomState(2)
    X = np.where(rng.rand(900, 10) < 0.1, rng.randn(900, 10), 0.0)
    cfg = Config.from_params({"objective": "binary", "verbose": -1})
    ref = CoreDataset.from_matrix(X, label=np.zeros(900), config=cfg)
    keep = [np.abs(X[:, j]) > 1e-35 for j in range(10)]
    ds = CoreDataset.from_sampled_columns(
        [X[:, j][keep[j]] for j in range(10)],
        [np.nonzero(keep[j])[0] for j in range(10)], 900, 900, config=cfg)
    csr = sp.csr_matrix(X)
    for s in range(0, 900, 250):
        part = csr[s:s + 250]
        ds.push_rows_csr(part.indptr, part.indices, part.data, s)
    ds.finish_load()
    np.testing.assert_array_equal(ds.group_bins, ref.group_bins)


def test_capi_sampled_column_push_flow():
    from lightgbm_tpu import capi
    rng = np.random.RandomState(3)
    X = rng.randn(600, 5)
    y = (X[:, 0] > 0).astype(float)
    keep = [np.abs(X[:, j]) > 1e-35 for j in range(5)]
    vals = [X[:, j][keep[j]] for j in range(5)]
    rows = [np.nonzero(keep[j])[0] for j in range(5)]
    out = [None]
    assert capi.LGBM_DatasetCreateFromSampledColumn(
        vals, rows, 5, [len(v) for v in vals], 600, 600,
        "objective=binary verbose=-1 num_leaves=7", out=out) == 0
    h = out[0]
    assert capi.LGBM_DatasetPushRows(h, X[:300], 300, 5, 0) == 0
    assert capi.LGBM_DatasetPushRows(h, X[300:], 300, 5, 300) == 0
    capi.LGBM_DatasetSetField(h, "label", y)
    bh = [None]
    assert capi.LGBM_BoosterCreate(
        h, "objective=binary verbose=-1 num_leaves=7", out=bh) == 0
    for _ in range(5):
        capi.LGBM_BoosterUpdateOneIter(bh[0], [None])
    pred = [None]
    capi.LGBM_BoosterPredictForMat(bh[0], X, out=pred)
    assert (((pred[0] > 0.5) == y).mean()) > 0.9


def test_streaming_construct_bounded_rss(tmp_path):
    """A CSV several times larger than the RSS budget constructs via
    two-round within the budget (subprocess for a clean ru_maxrss)."""
    code = r"""
import numpy as np, os, sys

import resource

BASE_PEAK_MB = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024

def vmrss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
path = sys.argv[1]
rng = np.random.RandomState(0)
# write ~600 MB of text: 1.5M rows x 25 cols.  One 20000-row chunk is
# formatted once and written 75 times — the bound under test is the
# construct's residency, which only sees row count and text size, and
# %-formatting 39M floats with savetxt would dominate the test's wall
# clock for no extra coverage.
import io
buf = io.StringIO()
np.savetxt(buf, rng.randn(20000, 26).astype(np.float32),
           delimiter=",", fmt="%.6g")
chunk_txt = buf.getvalue()
with open(path, "w") as f:
    for _ in range(75):
        f.write(chunk_txt)
write_mb = os.path.getsize(path) / 1e6
import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
cfg = Config.from_params({"objective": "regression", "verbose": -1,
                          "two_round": True, "max_bin": 63,
                          "bin_construct_sample_cnt": 20000})
# the bound is on what CONSTRUCT adds over the import baseline —
# an absolute bound silently re-fails every time the jax/numpy
# import footprint grows (and the peak watermark is polluted on
# this container: observed ~1.1-2.1 GB ru_maxrss at interpreter
# start), while the delta stays discriminating: uint8 bins
# (37.5 MB) + one parse chunk + sample buffers ~< 150 MB vs the
# 300 MB float64 matrix / ~600 MB resident text a densifying
# construct would hold.
rss_import_mb = vmrss_mb()
core = lgb.Dataset(path).construct(cfg)
assert core.num_data == 1_500_000, core.num_data
rss_mb = vmrss_mb()
peak_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print("csv_mb", write_mb, "rss_mb", rss_mb, "import", rss_import_mb,
      "peak", peak_mb, "base", BASE_PEAK_MB)
assert rss_mb - rss_import_mb < 300, (rss_import_mb, rss_mb)
if BASE_PEAK_MB < 400:
    # clean high-water mark: the TRANSIENT is visible too — a
    # construct that densifies then frees before returning (the 300
    # MB matrix would put the peak delta past the same budget) only
    # shows up here
    assert peak_mb - rss_import_mb < 300, (rss_import_mb, peak_mb)
"""
    r = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path / "big.csv")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=1200)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_qid_group_sizes_appearance_order():
    """Descending/unsorted qids keep appearance order (np.unique's
    sorted counts misassigned boundaries)."""
    from lightgbm_tpu.data_loader import qid_to_group_sizes
    np.testing.assert_array_equal(
        qid_to_group_sizes(np.array([5, 5, 3, 3, 3])), [2, 3])
    np.testing.assert_array_equal(
        qid_to_group_sizes(np.array([7, 2, 2, 9])), [1, 2, 1])
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        qid_to_group_sizes(np.array([1, 1, 2, 1]))  # non-contiguous
