"""sklearn-wrapper tests (reference tests/python_package_test/
test_sklearn.py:39-205)."""
import pickle

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_digits, make_regression
from sklearn.metrics import log_loss, mean_squared_error
from sklearn.model_selection import train_test_split

import lightgbm_tpu as lgb


def test_regressor():
    X, y = make_regression(n_samples=400, n_features=8, noise=5.0,
                           random_state=0)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, random_state=0)
    m = lgb.LGBMRegressor(n_estimators=30, silent=True)
    m.fit(X_tr, y_tr)
    mse = mean_squared_error(y_te, m.predict(X_te))
    base = mean_squared_error(y_te, np.full_like(y_te, y_tr.mean()))
    assert mse < 0.3 * base
    assert m.n_features_ == 8
    assert m.feature_importances_.sum() > 0


def test_classifier_binary():
    X, y = load_breast_cancer(return_X_y=True)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, random_state=0)
    m = lgb.LGBMClassifier(n_estimators=30, silent=True)
    m.fit(X_tr, y_tr)
    proba = m.predict_proba(X_te)
    assert proba.shape == (len(y_te), 2)
    assert log_loss(y_te, proba[:, 1]) < 0.25
    pred = m.predict(X_te)
    assert set(np.unique(pred)) <= set(m.classes_)
    assert (pred == y_te).mean() > 0.9


# re-tiered slow (tier-1 wall budget): multiclass semantics pinned fast by test_engine.py::test_multiclass;
# the wrapper surface by test_classifier_binary
@pytest.mark.slow
def test_classifier_multiclass():
    X, y = load_digits(n_class=4, return_X_y=True)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, random_state=0)
    m = lgb.LGBMClassifier(n_estimators=20, silent=True)
    m.fit(X_tr, y_tr)
    assert m.n_classes_ == 4
    proba = m.predict_proba(X_te)
    assert proba.shape == (len(y_te), 4)
    assert (m.predict(X_te) == y_te).mean() > 0.9


def test_classifier_string_labels():
    X, y = load_breast_cancer(return_X_y=True)
    ys = np.where(y > 0, "pos", "neg")
    m = lgb.LGBMClassifier(n_estimators=10, silent=True)
    m.fit(X, ys)
    pred = m.predict(X[:10])
    assert set(pred) <= {"pos", "neg"}


def test_ranker():
    rng = np.random.RandomState(0)
    n_q, per_q = 30, 20
    n = n_q * per_q
    X = rng.rand(n, 5)
    rel = (X[:, 0] * 4).astype(int).clip(0, 3)
    group = [per_q] * n_q
    m = lgb.LGBMRanker(n_estimators=20, silent=True,
                       min_child_samples=1)
    m.fit(X, rel, group=group)
    scores = m.predict(X)
    # higher relevance should get higher mean score
    assert scores[rel == 3].mean() > scores[rel == 0].mean()


# re-tiered slow (tier-1 wall budget): custom-objective semantics pinned fast by
# test_engine.py::test_custom_objective_fobj
@pytest.mark.slow
def test_custom_objective():
    X, y = load_breast_cancer(return_X_y=True)

    def logregobj(y_true, y_pred):
        p = 1.0 / (1.0 + np.exp(-y_pred))
        return p - y_true, p * (1 - p)

    m = lgb.LGBMClassifier(n_estimators=20, objective=logregobj,
                           silent=True)
    m.fit(X, y)
    raw = m.booster_.predict(X, raw_score=True)
    p = 1.0 / (1.0 + np.exp(-raw))
    assert log_loss(y, p) < 0.25


# re-tiered slow (tier-1 wall budget): dart semantics pinned fast by test_engine.py::test_dart
@pytest.mark.slow
def test_dart_sklearn():
    X, y = load_breast_cancer(return_X_y=True)
    m = lgb.LGBMClassifier(boosting_type="dart", n_estimators=20,
                           silent=True)
    m.fit(X, y)
    assert (m.predict(X) == y).mean() > 0.9


def test_clone_and_pickle():
    X, y = load_breast_cancer(return_X_y=True)
    m = lgb.LGBMClassifier(n_estimators=10, silent=True)
    params = m.get_params()
    m2 = lgb.LGBMClassifier(**params)
    assert m2.get_params()["n_estimators"] == 10
    m.fit(X, y)
    s = pickle.dumps(m.booster_)
    b = pickle.loads(s)
    assert np.allclose(b.predict(X[:5]),
                       m.booster_.predict(X[:5]))


# re-tiered slow (tier-1 wall budget): sklearn-integration surface pinned fast by test_clone_and_pickle
# + test_sklearn_check_estimator_basics
@pytest.mark.slow
def test_grid_search_compatible():
    from sklearn.model_selection import GridSearchCV
    X, y = load_breast_cancer(return_X_y=True)
    gs = GridSearchCV(lgb.LGBMClassifier(n_estimators=5, silent=True),
                      {"num_leaves": [7, 15]}, cv=2, scoring="accuracy")
    gs.fit(X, y)
    assert gs.best_score_ > 0.85


# re-tiered slow (tier-1 wall budget): early-stopping semantics pinned fast by
# test_engine.py::test_early_stopping
@pytest.mark.slow
def test_early_stopping_sklearn():
    X, y = load_breast_cancer(return_X_y=True)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, random_state=0)
    m = lgb.LGBMClassifier(n_estimators=120, silent=True,
                           learning_rate=0.3)
    m.fit(X_tr, y_tr, eval_set=[(X_te, y_te)],
          eval_metric="binary_logloss", early_stopping_rounds=5)
    assert m.best_iteration_ > 0
    assert m.booster_.num_trees() < 120


def test_sklearn_check_estimator_basics():
    """The reference integrates sklearn's own estimator checks
    (reference test_sklearn.py:185 TestSklearn.test_sklearn_integration).
    Run the core contract checks that don't require exotic input
    handling (sparse matrices are out of scope for the TPU backend)."""
    import numpy as np
    from sklearn.base import clone, is_classifier, is_regressor
    from sklearn.utils.validation import check_is_fitted
    import lightgbm_tpu as lgb

    reg = lgb.LGBMRegressor(n_estimators=5, num_leaves=7)
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7)
    assert is_regressor(reg) and is_classifier(clf)
    # get_params/set_params/clone round trip (sklearn contract)
    p = reg.get_params()
    assert p["n_estimators"] == 5
    reg2 = clone(reg).set_params(n_estimators=3)
    assert reg2.get_params()["n_estimators"] == 3
    assert reg.get_params()["n_estimators"] == 5  # clone is independent

    rng = np.random.RandomState(0)
    X = rng.randn(120, 4)
    yr = X[:, 0] * 2.0 + rng.randn(120) * 0.1
    yc = (X[:, 0] > 0).astype(int)
    reg.fit(X, yr)
    check_is_fitted(reg)
    assert reg.predict(X).shape == (120,)
    clf.fit(X, yc)
    assert set(clf.classes_) == {0, 1}
    proba = clf.predict_proba(X)
    assert proba.shape == (120, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    # refitting with different data must reset state
    X2 = rng.randn(80, 4)
    reg.fit(X2, X2[:, 1])
    assert reg.predict(X2).shape == (80,)


def test_apply_best_score_objective_properties():
    """reference sklearn.py tail: apply() leaf indices,
    best_score_ at the best iteration, objective_ resolution."""
    X, y = load_breast_cancer(return_X_y=True)
    clf = lgb.LGBMClassifier(n_estimators=8, num_leaves=15, verbose=-1)
    clf.fit(X, y, eval_set=[(X, y)], verbose=False)
    leaves = clf.apply(X)
    assert leaves.shape == (X.shape[0], 8)
    assert leaves.dtype.kind == "i"
    assert clf.objective_ == "binary"
    bs = clf.best_score_
    assert bs and all(
        np.isfinite(v) for d in bs.values() for v in d.values())
