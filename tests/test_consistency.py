"""Python<->CLI consistency over the shipped example configs — the
analog of the reference's tests/python_package_test/test_consistency.py
(:40-63): train through the CLI with each example's train.conf, train
the same config through the Python API, and require prediction
agreement to 5 decimals; also check file-loaded vs array-loaded
Dataset field equality."""
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import parse_args, run as cli_run
from lightgbm_tpu.config import Config

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, ".."))
EXAMPLES = os.path.join(ROOT, "examples")


def _ensure_example_data():
    marker = os.path.join(EXAMPLES, "binary_classification", "binary.train")
    if not os.path.exists(marker):
        subprocess.check_call(
            [sys.executable, os.path.join(EXAMPLES, "make_data.py")])


@pytest.mark.parametrize("example", ["binary_classification",
                                     "regression",
                                     "multiclass_classification",
                                     "lambdarank"])
@pytest.mark.slow
def test_cli_python_consistency(example, tmp_path, monkeypatch):
    _ensure_example_data()
    ex_dir = os.path.join(EXAMPLES, example)
    conf = os.path.join(ex_dir, "train.conf")
    if not os.path.exists(conf):
        pytest.skip(f"no train.conf for {example}")

    # ---- CLI training (data paths in the confs are repo-relative) ----
    monkeypatch.chdir(ROOT)
    model_path = str(tmp_path / "cli_model.txt")
    cli_run([f"config={conf}", f"output_model={model_path}",
             "num_iterations=6", "verbose=-1"])
    cli_bst = lgb.Booster(model_file=model_path)

    # ---- Python training with the same config ----
    kv = parse_args([f"config={conf}"])
    kv.update({"num_iterations": "6", "verbose": "-1"})
    kv.pop("output_model", None)
    kv.pop("config", None)
    kv.pop("task", None)
    data_path = os.path.join(ROOT, kv.pop("data"))
    kv.pop("valid_data", None)
    ds = lgb.Dataset(data_path, params=dict(kv))
    py_bst = lgb.train(dict(kv), ds, 6, verbose_eval=False)

    # ---- predictions agree to 5 decimals (reference standard) ----
    raw = np.loadtxt(data_path, delimiter="\t")
    X = raw[:, 1:]
    p_cli = cli_bst.predict(X)
    p_py = py_bst.predict(X)
    np.testing.assert_allclose(p_cli, p_py, atol=1e-5)


def test_file_vs_array_dataset_fields():
    _ensure_example_data()
    path = os.path.join(EXAMPLES, "binary_classification", "binary.train")
    raw = np.loadtxt(path, delimiter="\t")
    y, X = raw[:, 0], raw[:, 1:]
    cfg = Config.from_params({"verbose": -1})
    d_file = lgb.Dataset(path).construct(cfg)
    d_arr = lgb.Dataset(X, label=y).construct(cfg)
    assert d_file.num_data == d_arr.num_data
    assert d_file.num_features == d_arr.num_features
    np.testing.assert_allclose(d_file.metadata.label,
                               d_arr.metadata.label)
    np.testing.assert_array_equal(d_file.group_bins, d_arr.group_bins)
