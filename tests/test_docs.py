"""Generated documentation stays in sync with the code it documents."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parameters_md_in_sync():
    """docs/Parameters.md is generated from lightgbm_tpu/config.py —
    a Config field added/changed without regenerating must fail here
    (run: python scripts/gen_parameter_docs.py).  The generator itself
    asserts every Config field is emitted and that parsed defaults
    literal-eval to the live dataclass defaults."""
    run = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "gen_parameter_docs.py"),
         "--check"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert run.returncode == 0, run.stderr or run.stdout
