#!/bin/sh
# Build the reference LightGBM CLI (CPU-only) into .refbuild/ so the
# interop parity tests (tests/test_reference_parity.py) can run.  The
# binary is deliberately NOT committed to git (opaque 1.7 MB ELF,
# platform-specific); run this once per checkout:
#
#   sh tests/build_reference.sh [/path/to/reference]
#
# Takes a few minutes on one core.
set -e
REF_SRC="${1:-/root/reference}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUILD_DIR="$REPO_ROOT/.refbuild"
mkdir -p "$BUILD_DIR"
cd "$BUILD_DIR"
cmake -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON "$REF_SRC" \
    > cmake.log 2>&1
make -j"$(nproc)" lightgbm > make.log 2>&1
# the reference CMake sets EXECUTABLE_OUTPUT_PATH to ITS source dir;
# move the ELF here and leave the read-only reference tree untouched
if [ -f "$REF_SRC/lightgbm" ]; then
    mv "$REF_SRC/lightgbm" "$BUILD_DIR/lightgbm"
fi
if [ ! -f "$BUILD_DIR/lightgbm" ]; then
    echo "ERROR: no binary at $BUILD_DIR/lightgbm (see make.log)" >&2
    exit 1
fi
echo "built: $BUILD_DIR/lightgbm"
