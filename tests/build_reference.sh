#!/bin/sh
# Build the reference LightGBM CLI (CPU-only) into .refbuild/ so the
# interop parity tests (tests/test_reference_parity.py) can run.  The
# binary is deliberately NOT committed to git (opaque 1.7 MB ELF,
# platform-specific); run this once per checkout:
#
#   sh tests/build_reference.sh [/path/to/reference]
#
# Takes a few minutes on one core.
set -e
REF_SRC="${1:-/root/reference}"
REPO_ROOT="$(cd "$(dirname "$0")/.." && pwd)"
BUILD_DIR="$REPO_ROOT/.refbuild"
mkdir -p "$BUILD_DIR"
cd "$BUILD_DIR"
cmake -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON "$REF_SRC" \
    > cmake.log 2>&1
make -j"$(nproc)" lightgbm > make.log 2>&1
echo "built: $BUILD_DIR/lightgbm"
