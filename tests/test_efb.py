"""Exclusive feature bundling tests (reference dataset.cpp:66-210)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config


def _onehot_data(n=1000, cats=8, seed=0):
    rng = np.random.RandomState(seed)
    z = rng.randint(0, cats, size=n)
    onehot = (z[:, None] == np.arange(cats)[None, :]).astype(float)
    dense = rng.randn(n, 2)
    X = np.column_stack([onehot, dense])
    y = (np.isin(z, [1, 3]) | (dense[:, 0] > 1.0)).astype(float)
    return X, y, z


def test_bundles_exclusive_features():
    X, y, _ = _onehot_data()
    cfg = Config.from_params({"objective": "binary", "verbose": -1})
    core = lgb.Dataset(X, label=y).construct(cfg)
    # 8 mutually-exclusive one-hot columns pack into one group;
    # the 2 dense columns stay separate
    assert core.num_groups < core.num_features
    assert any(len(b) > 1 for b in core._bundles)


def test_bundled_training_correct():
    X, y, z = _onehot_data()
    params = {"objective": "binary", "verbose": -1, "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, 30, verbose_eval=False)
    pred = bst.predict(X)
    acc = ((pred > 0.5) == y).mean()
    assert acc > 0.95


def test_bundling_disabled():
    X, y, _ = _onehot_data()
    cfg = Config.from_params({"objective": "binary", "verbose": -1,
                              "enable_bundle": False})
    core = lgb.Dataset(X, label=y).construct(cfg)
    assert core.num_groups == core.num_features


def test_bundle_vs_unbundled_same_predictions():
    X, y, _ = _onehot_data(600, 6, seed=3)
    p1 = {"objective": "binary", "verbose": -1, "min_data_in_leaf": 5}
    p2 = dict(p1, enable_bundle=False)
    b1 = lgb.train(p1, lgb.Dataset(X, label=y), 15, verbose_eval=False)
    b2 = lgb.train(p2, lgb.Dataset(X, label=y), 15, verbose_eval=False)
    # The first tree is bit-identical; later trees may pick a different
    # split when two candidates TIE in gain, because FixHistogram
    # reconstructs a bundle's shared default slot as total - sum — a
    # float-summation-order difference in the last ulp that flips the
    # argmax between equal-gain candidates (the reference shares this
    # property; its suite never compares bundled vs unbundled models).
    # So: tree 1 exact, full model loose in aggregate.
    assert np.allclose(b1.predict(X, num_iteration=1),
                       b2.predict(X, num_iteration=1), atol=1e-6)
    d = np.abs(b1.predict(X) - b2.predict(X))
    assert d.mean() < 5e-3 and d.max() < 5e-2
    agree = ((b1.predict(X) > 0.5) == (b2.predict(X) > 0.5)).mean()
    assert agree > 0.99
