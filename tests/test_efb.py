"""Exclusive feature bundling tests (reference dataset.cpp:66-210)."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config


def _onehot_data(n=1000, cats=8, seed=0):
    rng = np.random.RandomState(seed)
    z = rng.randint(0, cats, size=n)
    onehot = (z[:, None] == np.arange(cats)[None, :]).astype(float)
    dense = rng.randn(n, 2)
    X = np.column_stack([onehot, dense])
    y = (np.isin(z, [1, 3]) | (dense[:, 0] > 1.0)).astype(float)
    return X, y, z


def test_bundles_exclusive_features():
    X, y, _ = _onehot_data()
    cfg = Config.from_params({"objective": "binary", "verbose": -1})
    core = lgb.Dataset(X, label=y).construct(cfg)
    # 8 mutually-exclusive one-hot columns pack into one group;
    # the 2 dense columns stay separate
    assert core.num_groups < core.num_features
    assert any(len(b) > 1 for b in core._bundles)


def test_bundled_training_correct():
    X, y, z = _onehot_data()
    params = {"objective": "binary", "verbose": -1, "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, 30, verbose_eval=False)
    pred = bst.predict(X)
    acc = ((pred > 0.5) == y).mean()
    assert acc > 0.95


def test_bundling_disabled():
    X, y, _ = _onehot_data()
    cfg = Config.from_params({"objective": "binary", "verbose": -1,
                              "enable_bundle": False})
    core = lgb.Dataset(X, label=y).construct(cfg)
    assert core.num_groups == core.num_features


def test_bundle_vs_unbundled_same_predictions():
    X, y, _ = _onehot_data(600, 6, seed=3)
    p1 = {"objective": "binary", "verbose": -1, "min_data_in_leaf": 5}
    p2 = dict(p1, enable_bundle=False)
    b1 = lgb.train(p1, lgb.Dataset(X, label=y), 15, verbose_eval=False)
    b2 = lgb.train(p2, lgb.Dataset(X, label=y), 15, verbose_eval=False)
    # early trees are bit-identical; later ones may tie-break
    # differently on ~zero-gain splits (FixHistogram reconstructs the
    # shared default slot as total - sum, a float-order difference the
    # reference shares), so compare few-tree predictions exactly and
    # full-model predictions loosely
    assert np.allclose(b1.predict(X, num_iteration=5),
                       b2.predict(X, num_iteration=5), atol=1e-5)
    assert np.abs(b1.predict(X) - b2.predict(X)).mean() < 5e-3
