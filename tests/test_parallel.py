"""Distributed tree-learner tests on the 8-device virtual CPU mesh —
the deterministic multi-host substitute the reference lacks (SURVEY §4:
socket-mode multi-machine was only exercised manually)."""
import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple (virtual) devices")


def _data(n=1200, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(n) > 0).astype(float)
    return X, y


def _train(X, y, learner, **extra):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": learner, "metric": "binary_logloss"}
    params.update(extra)
    er = {}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, 10, valid_sets=[ds], evals_result=er,
                    verbose_eval=False)
    return bst, er["training"]["binary_logloss"][-1]


def test_data_parallel_matches_serial():
    X, y = _data()
    bst_s, ll_s = _train(X, y, "serial")
    bst_d, ll_d = _train(X, y, "data")
    # same algorithm, different reduction order: near-identical metrics
    assert abs(ll_s - ll_d) < 1e-3
    ps = bst_s.predict(X[:200])
    pd = bst_d.predict(X[:200])
    assert np.max(np.abs(ps - pd)) < 1e-2


def test_feature_parallel_matches_serial():
    X, y = _data()
    bst_s, ll_s = _train(X, y, "serial")
    bst_f, ll_f = _train(X, y, "feature")
    assert abs(ll_s - ll_f) < 1e-3


def test_voting_parallel_trains():
    X, y = _data()
    bst_v, ll_v = _train(X, y, "voting")
    assert ll_v < 0.4


def test_explicit_mesh_shape():
    X, y = _data(600, 6)
    bst, ll = _train(X, y, "data", mesh_shape=(4,), mesh_axes=("data",))
    assert ll < 0.4


def test_sharded_bins_placement():
    """The bin matrix must actually be sharded over the mesh rows."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.grower import TreeGrower
    X, y = _data(800, 5)
    cfg = Config.from_params({"objective": "binary",
                              "tree_learner": "data", "verbose": -1})
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    assert g.policy.mesh is not None
    shard_shapes = {s.data.shape for s in g.bins.addressable_shards}
    n_dev = len(jax.devices())
    assert len(g.bins.addressable_shards) == n_dev
    assert all(s[0] == g.n_padded // n_dev for s in shard_shapes)
