"""Distributed tree-learner tests on the 8-device virtual CPU mesh —
the deterministic multi-host substitute the reference lacks (SURVEY §4:
socket-mode multi-machine was only exercised manually)."""
import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple (virtual) devices")


def _data(n=1200, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(n) > 0).astype(float)
    return X, y


def _train(X, y, learner, **extra):
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "tree_learner": learner, "metric": "binary_logloss"}
    params.update(extra)
    er = {}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train(params, ds, 10, valid_sets=[ds], evals_result=er,
                    verbose_eval=False)
    return bst, er["training"]["binary_logloss"][-1]


def test_data_parallel_matches_serial():
    X, y = _data()
    bst_s, ll_s = _train(X, y, "serial")
    bst_d, ll_d = _train(X, y, "data")
    # same algorithm, different reduction order: near-identical metrics
    assert abs(ll_s - ll_d) < 1e-3
    ps = bst_s.predict(X[:200])
    pd = bst_d.predict(X[:200])
    assert np.max(np.abs(ps - pd)) < 1e-2


def test_feature_parallel_matches_serial():
    X, y = _data()
    bst_s, ll_s = _train(X, y, "serial")
    bst_f, ll_f = _train(X, y, "feature")
    assert abs(ll_s - ll_f) < 1e-3


def test_voting_parallel_trains():
    X, y = _data()
    bst_v, ll_v = _train(X, y, "voting")
    assert ll_v < 0.4


def test_explicit_mesh_shape():
    X, y = _data(600, 6)
    bst, ll = _train(X, y, "data", mesh_shape=(4,), mesh_axes=("data",))
    assert ll < 0.4


def test_sharded_bins_placement():
    """The bin matrix must actually be sharded over the mesh rows."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.grower import TreeGrower
    X, y = _data(800, 5)
    cfg = Config.from_params({"objective": "binary",
                              "tree_learner": "data", "verbose": -1})
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    assert g.policy.mesh is not None
    shard_shapes = {s.data.shape for s in g.bins.addressable_shards}
    n_dev = len(jax.devices())
    assert len(g.bins.addressable_shards) == n_dev
    assert all(s[0] == g.n_padded // n_dev for s in shard_shapes)


def test_voting_reduces_histogram_exchange_volume():
    """PV-Tree's point (reference voting_parallel_tree_learner.cpp):
    only the top-2k voted features' histograms cross the network.
    Structural pin: the jaxpr of one voting round psums (a) the (L, F)
    vote matrix and (b) an (L, 2k, B, 3) compact histogram — NEVER a
    full (L, F, B, 3) tensor."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.grower import TreeGrower

    X, y = _data(1200, 40, seed=3)
    top_k = 5
    cfg = Config.from_params({"objective": "binary", "num_leaves": 15,
                              "verbose": -1, "tree_learner": "voting",
                              "top_k": top_k})
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    F = g.num_features
    assert F == 40

    import jax.numpy as jnp
    grad = jnp.zeros(g.n_padded, jnp.float32)
    hess = jnp.ones(g.n_padded, jnp.float32)
    cnt = jnp.ones(g.n_padded, jnp.float32)
    fmask = jnp.ones(F, bool)
    st = g._init_state(grad, hess, cnt)
    jaxpr = jax.make_jaxpr(
        lambda s, gr, h, c, m: g._voting_find_splits(s, gr, h, c, m))(
        st, grad, hess, cnt, fmask)
    psum_shapes = []
    def walk(jx):
        for eqn in jx.eqns:
            if "psum" in eqn.primitive.name:
                psum_shapes.extend(tuple(v.aval.shape)
                                   for v in eqn.invars)
            for v in eqn.params.values():
                for w in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(w, "eqns"):
                        walk(w)
                    elif hasattr(w, "jaxpr"):
                        walk(w.jaxpr)
    walk(jaxpr.jaxpr)
    assert psum_shapes, "no psum found — collective structure changed?"
    B = g.max_feature_bin
    for shp in psum_shapes:
        if len(shp) == 4:
            # compact histogram exchange: feature dim == 2k, not F
            assert shp[1] == 2 * top_k, shp
        else:
            # the vote matrix (L, F) — F floats/leaf, not F*B*3
            assert len(shp) <= 2, shp
    full = 15 * F * B * 3
    compact = 15 * 2 * top_k * B * 3 + 15 * F
    assert compact < full / 3  # the claimed volume reduction


# re-tiered slow (tier-1 wall budget): the voting plan itself stays
# pinned fast by test_voting_parallel_trains +
# test_voting_reduces_histogram_exchange_volume
@pytest.mark.slow
def test_voting_accuracy_near_data_parallel_wide_features():
    """Accuracy check on num_features >> top_k (VERDICT weak #7): the
    voting election must be NEAR-PARITY with the full exchange
    (PV-Tree's claim, voting_parallel_tree_learner.cpp:166-195) — the
    r4 verdict flagged the old 1.25x+0.02 slack as loose enough to
    mask a real election regression."""
    X, y = _data(1500, 40, seed=4)
    bst_d, ll_d = _train(X, y, "data")
    bst_v, ll_v = _train(X, y, "voting", top_k=5)
    assert ll_v < ll_d * 1.05 + 0.01, (ll_v, ll_d)


def test_feature_parallel_shard_map_matches_serial():
    """The vertical-partition shard_map path (num_groups divisible by
    the mesh) must match serial EXACTLY — the election is a global
    argmax over per-shard exact finders, so unlike voting there is no
    approximation (reference feature_parallel_tree_learner.cpp's
    SyncUpGlobalBestSplit elects the same split serial would find)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.grower import TreeGrower

    n_dev = len(jax.devices())
    X, y = _data(1600, 16, seed=5)
    cfg = Config.from_params({"objective": "binary",
                              "tree_learner": "feature", "verbose": -1})
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    if g.num_groups % n_dev == 0:
        assert g._is_feature_par, "divisible groups must take the " \
            "shard_map vertical-partition path"
    bst_s, ll_s = _train(X, y, "serial")
    bst_f, ll_f = _train(X, y, "feature")
    np.testing.assert_allclose(bst_s.predict(X[:300]),
                               bst_f.predict(X[:300]), atol=1e-5)
    assert abs(ll_s - ll_f) < 1e-4
