"""Device-vs-host predict parity across the serving predictor's full
semantic surface (round-8 tentpole: the ensemble-vectorized level
descent replaces the per-tree scan walk).

Every device implementation — the level descent (default), its Pallas
row-tile form (interpret seam; the container has no chip) and the
legacy per-tree scan kept as the A/B — must route every row exactly
like the host float64 tree walk: categorical splits, all three
missing-value modes (MISSING_NAN / MISSING_ZERO / the zero-threshold
band), +-inf thresholds (regression pin for the r7 `thr_lo = inf - inf`
NaN fix, extended round 8 to +-inf DATA against +-inf thresholds),
`num_leaves == 1` stumps, batch sizes straddling the power-of-two
bucket boundaries, and identical `num_iteration`/`raw_score`
resolution on both paths.
"""
import functools

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config

IMPLS = ("level", "pallas", "scan")


def _clone(bst, impl):
    """Reload a trained model as a serving-shaped (loaded) booster
    pinned to one device predictor implementation."""
    cfg = Config.from_params({
        "predict_kernel": impl, "verbose": -1,
        # the Pallas variant runs on the interpret seam in this
        # container (no chip); tile < min bucket exercises the grid
        "force_pallas_interpret": impl == "pallas",
        "predict_pallas_tile": 8,
    })
    return lgb.Booster(config=cfg, model_str=bst.model_to_string())


def _assert_parity(bst, impl, X, **kw):
    dev = _clone(bst, impl).predict(X, device=True, **kw)
    host = bst.predict(X, device=False, **kw)
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-7)


def _train(X, y, extra=None, iters=8, **dskw):
    params = {"objective": "regression", "verbose": -1,
              "num_leaves": 15, "min_data_in_leaf": 5}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y, **dskw), iters,
                     verbose_eval=False)


@functools.lru_cache(maxsize=None)
def _missing_case(mode):
    """One trained model per missing mode, shared by every impl param
    (training dominates these tests; prediction is the subject)."""
    rng = np.random.RandomState(3)
    X = rng.randn(400, 5)
    if mode == "nan":
        X[rng.rand(400, 5) < 0.1] = np.nan
    if mode == "zero":
        X[rng.rand(400, 5) < 0.2] = 0.0
    y = np.nan_to_num(X[:, 0]) - 0.5 * np.nan_to_num(X[:, 1])
    extra = {"zero_as_missing": mode == "zero",
             "use_missing": mode != "none"}
    bst = _train(X, y, extra)
    # probe rows the training draw may not cover: NaN everywhere,
    # exact zeros, and sub-threshold values inside the zero band
    probe = np.vstack([X, np.full((2, 5), np.nan),
                       np.zeros((2, 5)), np.full((2, 5), 1e-40),
                       np.full((2, 5), -1e-40)])
    return bst, probe


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("mode", ["nan", "zero", "none"])
def test_missing_mode_parity(impl, mode):
    bst, probe = _missing_case(mode)
    _assert_parity(bst, impl, probe)


@functools.lru_cache(maxsize=None)
def _categorical_case():
    rng = np.random.RandomState(5)
    X = rng.randn(500, 4)
    X[:, -1] = rng.randint(0, 12, 500)
    y = (X[:, -1] % 3 == 0).astype(float) + 0.2 * X[:, 0]
    bst = _train(X, y, {"max_cat_to_onehot": 2}, iters=10,
                 categorical_feature=[3])
    probe = np.vstack([X, [[0.0, 0.0, 0.0, 25.0]],   # unseen category
                       [[0.0, 0.0, 0.0, -3.0]],      # negative
                       [[0.0, 0.0, 0.0, np.nan]]])   # NaN category
    return bst, probe


@pytest.mark.parametrize("impl", IMPLS)
def test_categorical_parity(impl):
    bst, probe = _categorical_case()
    _assert_parity(bst, impl, probe)


def _model_text(tree_blocks, max_feature_idx=1):
    names = " ".join(f"f{i}" for i in range(max_feature_idx + 1))
    infos = " ".join("[-1e+30:1e+30]"
                     for _ in range(max_feature_idx + 1))
    head = "\n".join([
        "tree", "version=v2", "num_class=1",
        "num_tree_per_iteration=1", "label_index=0",
        f"max_feature_idx={max_feature_idx}", "objective=regression",
        f"feature_names={names}", f"feature_infos={infos}",
        "tree_sizes=" + " ".join(str(len(b)) for b in tree_blocks),
        "", ""])
    return head + "".join(f"Tree={i}\n{b}\n"
                          for i, b in enumerate(tree_blocks))


_INF_TREE = """num_leaves=3
num_cat=0
split_feature=0 1
split_gain=1 1
threshold=inf -inf
decision_type={dt} {dt}
left_child=1 -1
right_child=-1 -2
leaf_value=0.5 -1.25 2.75
leaf_count=2 2 2
internal_value=0 0
internal_count=6 4
shrinkage=1
"""

_STUMP_TREE = """num_leaves=1
num_cat=0
leaf_value=0.625
leaf_count=7
shrinkage=1
"""

_PLAIN_TREE = """num_leaves=2
num_cat=0
split_feature=0
split_gain=1
threshold=0.25
decision_type=2
left_child=-1
right_child=-2
leaf_value=1.5 -0.75
leaf_count=3 4
internal_value=0
internal_count=7
shrinkage=1
"""


@pytest.mark.parametrize("impl", IMPLS)
@pytest.mark.parametrize("dt", [0, 8])  # missing none / missing NaN
def test_inf_threshold_parity(impl, dt):
    """+-inf saved thresholds (a split isolating the overflow bin) must
    route identically on device — including +-inf DATA values, where a
    naive two-float compare computes inf - inf = NaN and misroutes
    (host: `inf <= inf` is True)."""
    text = _model_text([_INF_TREE.format(dt=dt)])
    host_b = lgb.Booster(model_str=text)
    vals = [-np.inf, -5.0, 0.0, 5.0, np.inf, np.nan]
    probe = np.array([[a, b] for a in vals for b in vals])
    dev = _clone(host_b, impl).predict(probe, device=True)
    host = host_b.predict(probe, device=False)
    np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("impl", IMPLS)
def test_stump_ensemble_parity(impl):
    """num_leaves == 1 trees (no split cleared the gain bar) settle at
    their single leaf in zero levels — mixed with real trees, the
    flat-node encoding must still land every tree's contribution."""
    text = _model_text([_STUMP_TREE, _PLAIN_TREE, _STUMP_TREE])
    host_b = lgb.Booster(model_str=text)
    probe = np.array([[-1.0, 0.0], [0.25, 1.0], [0.2500001, -1.0],
                      [np.nan, np.nan], [3.0, 2.0]])
    dev = _clone(host_b, impl).predict(probe, device=True)
    host = host_b.predict(probe, device=False)
    np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)
    # all-stump ensemble: depth 0, nothing to descend
    text1 = _model_text([_STUMP_TREE, _STUMP_TREE])
    b1 = lgb.Booster(model_str=text1)
    dev1 = _clone(b1, impl).predict(probe, device=True)
    np.testing.assert_allclose(dev1, b1.predict(probe, device=False),
                               rtol=1e-6, atol=1e-7)


@functools.lru_cache(maxsize=None)
def _boundary_case():
    rng = np.random.RandomState(11)
    X = rng.randn(70, 5)
    y = X[:, 0] - 0.3 * X[:, 2]
    return _train(X, y), X


@pytest.mark.parametrize("impl", IMPLS)
def test_bucket_boundary_batch_sizes(impl):
    """Batch sizes straddling the power-of-two buckets (15/16/17 around
    the default min bucket 16) must score identically — the padded tail
    rows are discarded, never leaked."""
    bst, X = _boundary_case()
    dev_b = _clone(bst, impl)
    for n in (1, 15, 16, 17, 31, 32, 33, 70):
        dev = dev_b.predict(X[:n], device=True)
        host = bst.predict(X[:n], device=False)
        np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-7,
                                   err_msg=f"batch size {n}")


@functools.lru_cache(maxsize=None)
def _binary_case():
    rng = np.random.RandomState(13)
    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(float)
    return _train(X, y, {"objective": "binary"}, iters=9), X


@pytest.mark.parametrize("impl", IMPLS)
def test_num_iteration_and_raw_score_identical(impl):
    """num_iteration truncation (incl. best_iteration resolution) and
    raw_score conversion must resolve identically on both paths."""
    bst, X = _binary_case()
    dev_b = _clone(bst, impl)
    for ni in (-1, 1, 4, 9, 50):
        for raw in (False, True):
            dev = dev_b.predict(X, device=True, num_iteration=ni,
                                raw_score=raw)
            host = bst.predict(X, device=False, num_iteration=ni,
                               raw_score=raw)
            np.testing.assert_allclose(
                dev, host, rtol=2e-5, atol=2e-7,
                err_msg=f"num_iteration={ni} raw_score={raw}")
    # best_iteration resolution: both paths must slice the same count
    # (restore afterwards — the trained booster is shared across the
    # impl parametrization)
    try:
        bst.best_iteration = 3
        dev_b.best_iteration = 3
        np.testing.assert_allclose(
            dev_b.predict(X, device=True),
            bst.predict(X, device=False), rtol=2e-5, atol=2e-7)
    finally:
        bst.best_iteration = -1
