"""Crash-isolated driver for tests/test_capi.py (round 7).

The C-API suite used to die intermittently in native code on this
container (SIGABRT/SIGSEGV mid-suite or at interpreter exit) — traced
in r7 to jax buffer donation on the per-iteration `_fused_step`
corrupting the heap once several booster shapes jit it in one process,
and fixed by dropping that donation (gbdt.py).  Run in-process, such a
crash killed the pytest worker and discarded every result after it.
As defense-in-depth against any recurrence, this driver runs the
module in a CHILD pytest with LGBM_CAPI_INPROC=1 and asserts on the
child's report, so:

- a genuine test FAILURE in the child = this test fails immediately
  with the child's output (no retry — real regressions stay loud),
- a mid-suite native crash (no summary line) = retried up to
  ATTEMPTS times; only a persistent crash fails, so the known
  intermittent container glitch doesn't flake the tier-1 suite while
  an every-time crash (a real native regression) still reports, and
- an exit-time crash AFTER all child tests passed = still a PASS
  (the summary line is the verdict, not the interpreter's rc).
"""
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ATTEMPTS = 3


def _run_child():
    env = dict(os.environ, LGBM_CAPI_INPROC="1")
    run = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         os.path.join(REPO, "tests", "test_capi.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    out = (run.stdout or "") + (run.stderr or "")
    return run.returncode, out


def test_capi_suite_in_subprocess():
    for attempt in range(1, ATTEMPTS + 1):
        rc, out = _run_child()
        tail = out[-4000:]
        summary = re.search(r"(\d+) passed", out)
        crashed = rc not in (0, 1)        # signal/abort exit codes

        if re.search(r"\d+ failed", out):
            raise AssertionError(
                f"C-API child reported test failures "
                f"(attempt {attempt}):\n{tail}")
        if re.search(r"\d+ errors?\b", out) or rc in (2, 3, 4, 5):
            # deterministic pytest-level failure (collection/import/
            # usage error or nothing collected, exit codes 2-5) —
            # report it immediately instead of burning ATTEMPTS
            # retries and blaming the native-crash container glitch
            raise AssertionError(
                f"C-API child failed to collect/run (rc={rc}, "
                f"attempt {attempt}):\n{tail}")
        if summary:
            n_passed = int(summary.group(1))
            assert n_passed >= 6, (
                f"C-API child only ran {n_passed} tests — collection "
                f"shrank:\n{tail}")
            if crashed:
                # every test passed and THEN the interpreter died — the
                # known exit-time native glitch; record without failing
                print(f"note: C-API child crashed at exit (rc={rc}) "
                      f"after {n_passed} passed — known container "
                      f"glitch", file=sys.stderr)
            return
        # no summary: the child died mid-suite before reporting
        print(f"note: C-API child crashed mid-suite (rc={rc}, attempt "
              f"{attempt}/{ATTEMPTS}) — retrying", file=sys.stderr)
    raise AssertionError(
        f"C-API child crashed on all {ATTEMPTS} attempts "
        f"(rc={rc}{' — native crash' if crashed else ''}):\n{tail}")
