/*
 * Standalone C host exercising the native embedding C API
 * (lightgbm_tpu/native/include/lightgbm_tpu_c_api.h) the way the
 * reference's C API test drives lib_lightgbm
 * (reference: tests/c_api_test/test_.py) — dataset from a C matrix,
 * train, eval, predict, model round-trip — but from a pure C program
 * with no Python on the stack.
 *
 * Exits 0 and prints "NATIVE_CAPI_OK" on success.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>

#include "lightgbm_tpu_c_api.h"

#define CHECK(call)                                                  \
  do {                                                               \
    if ((call) != 0) {                                               \
      fprintf(stderr, "FAILED %s: %s\n", #call, LGBM_GetLastError()); \
      FAIL(1);                                                      \
    }                                                                \
  } while (0)

/* verdicts leave through _exit (see the embedding caveat in
 * lightgbm_tpu/native/README.md: the embedded CPython + jax thread
 * pools make glibc DSO-destructor order hostile after main returns) */
#define FAIL(code) do { fflush(NULL); _exit(code); } while (0)

int main(int argc, char** argv) {
  if (argc > 1) LTPU_AddSysPath(argv[1]);
  CHECK(LTPU_EnsureInitialized());

  /* synthetic binary task: y = x0 + x1 > 0, 400 rows x 4 features */
  const int n = 400, f = 4;
  double* X = (double*)malloc(sizeof(double) * n * f);
  float* y = (float*)malloc(sizeof(float) * n);
  unsigned s = 123456789u;
  for (int i = 0; i < n; ++i) {
    double row_sum = 0.0;
    for (int j = 0; j < f; ++j) {
      s = s * 1103515245u + 12345u;
      double v = ((double)(s >> 16) / 32768.0) - 1.0; /* [-1, 1) */
      X[i * f + j] = v;
      if (j < 2) row_sum += v;
    }
    y[i] = row_sum > 0.0 ? 1.0f : 0.0f;
  }

  DatasetHandle ds = NULL;
  CHECK(LGBM_DatasetCreateFromMat(X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  "max_bin=31 verbose=-1", NULL, &ds));
  CHECK(LGBM_DatasetSetField(ds, "label", y, n, C_API_DTYPE_FLOAT32));

  int32_t num_data = 0, num_feat = 0;
  CHECK(LGBM_DatasetGetNumData(ds, &num_data));
  CHECK(LGBM_DatasetGetNumFeature(ds, &num_feat));
  if (num_data != n || num_feat != f) {
    fprintf(stderr, "dataset dims wrong: %d x %d\n", num_data, num_feat);
    FAIL(1);
  }

  BoosterHandle bst = NULL;
  CHECK(LGBM_BoosterCreate(
      ds,
      "objective=binary num_leaves=15 min_data_in_leaf=5 "
      "learning_rate=0.2 verbose=-1 metric=binary_logloss",
      &bst));
  for (int it = 0; it < 20; ++it) {
    int fin = 0;
    CHECK(LGBM_BoosterUpdateOneIter(bst, &fin));
  }
  int iter = 0;
  CHECK(LGBM_BoosterGetCurrentIteration(bst, &iter));
  if (iter != 20) {
    fprintf(stderr, "iteration count wrong: %d\n", iter);
    FAIL(1);
  }

  int eval_count = 0;
  CHECK(LGBM_BoosterGetEvalCounts(bst, &eval_count));
  if (eval_count < 1) {
    fprintf(stderr, "eval count wrong: %d\n", eval_count);
    FAIL(1);
  }
  double* evals = (double*)malloc(sizeof(double) * eval_count);
  int eval_len = 0;
  CHECK(LGBM_BoosterGetEval(bst, 0, &eval_len, evals));
  if (eval_len < 1 || !(evals[0] < 0.5)) {
    fprintf(stderr, "train logloss did not improve: n=%d v=%f\n", eval_len,
            eval_len > 0 ? evals[0] : -1.0);
    FAIL(1);
  }

  int64_t pred_len = 0;
  double* preds = (double*)malloc(sizeof(double) * n);
  CHECK(LGBM_BoosterPredictForMat(bst, X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  C_API_PREDICT_NORMAL, -1, "", &pred_len,
                                  preds));
  if (pred_len != n) {
    fprintf(stderr, "pred_len wrong: %lld\n", (long long)pred_len);
    FAIL(1);
  }
  int correct = 0;
  for (int i = 0; i < n; ++i) {
    if (!(preds[i] >= 0.0 && preds[i] <= 1.0) || isnan(preds[i])) {
      fprintf(stderr, "pred out of range at %d: %f\n", i, preds[i]);
      FAIL(1);
    }
    if ((preds[i] > 0.5) == (y[i] > 0.5f)) ++correct;
  }
  if (correct < (int)(0.9 * n)) {
    fprintf(stderr, "train accuracy too low: %d/%d\n", correct, n);
    FAIL(1);
  }

  /* model string round-trip: save, reload, predictions must match */
  int64_t str_len = 0;
  CHECK(LGBM_BoosterSaveModelToString(bst, -1, 0, &str_len, NULL));
  char* model = (char*)malloc((size_t)str_len);
  CHECK(LGBM_BoosterSaveModelToString(bst, -1, str_len, &str_len, model));
  BoosterHandle bst2 = NULL;
  int loaded_iters = 0;
  CHECK(LGBM_BoosterLoadModelFromString(model, &loaded_iters, &bst2));
  double* preds2 = (double*)malloc(sizeof(double) * n);
  CHECK(LGBM_BoosterPredictForMat(bst2, X, C_API_DTYPE_FLOAT64, n, f, 1,
                                  C_API_PREDICT_NORMAL, -1, "", &pred_len,
                                  preds2));
  for (int i = 0; i < n; ++i) {
    if (fabs(preds[i] - preds2[i]) > 1e-6) {
      fprintf(stderr, "round-trip mismatch at %d: %f vs %f\n", i, preds[i],
              preds2[i]);
      FAIL(1);
    }
  }

  /* feature importance: the two informative features should lead */
  double imp[4];
  CHECK(LGBM_BoosterFeatureImportance(bst, -1, 0, imp));
  if (imp[0] + imp[1] <= imp[2] + imp[3]) {
    fprintf(stderr, "importance order wrong: %f %f %f %f\n", imp[0], imp[1],
            imp[2], imp[3]);
    FAIL(1);
  }

  CHECK(LGBM_BoosterFree(bst2));
  CHECK(LGBM_BoosterFree(bst));
  CHECK(LGBM_DatasetFree(ds));
  free(evals);
  free(preds2);
  free(model);
  free(preds);
  free(X);
  free(y);
  printf("NATIVE_CAPI_OK\n");
  FAIL(0);
}
