/* C host that EXECUTES the JNI binding (jni/lightgbm_jni.c) without a
 * JVM: fabricates a JNIEnv function table (string/array accessors,
 * exception raise) and drives the full SWIG-breadth surface through
 * the Java_* entry points against the real liblgbm_tpu.so — dataset
 * create (mat/CSR/subset/reference), train, valid-set eval flow,
 * dense/CSR predict parity, model string/file round trips, custom
 * objective iteration, rollback, merge, leaf mutation, feature names,
 * file prediction.  With a JDK present the same binding builds against
 * the genuine <jni.h> and runs under a real JVM (see
 * jni/LightGBMNative.java). */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>
#include <string.h>

#include "../jni/jni_min.h"

/* ---- fake object model ------------------------------------------- */
typedef struct _jobject {
  int kind; /* 0 string, 1 double[], 2 class, 3 int[], 4 float[],
               5 object[] */
  const char* str;
  double* d;
  jint* i;
  jfloat* f;
  jobject* o;
  jsize len;
} FakeObj;

static jobject mk_string(const char* s) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 0;
  o->str = s;
  return o;
}

static jobject mk_darray(const double* v, jsize n) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 1;
  o->d = malloc(sizeof(double) * (size_t)n);
  if (v) memcpy(o->d, v, sizeof(double) * (size_t)n);
  o->len = n;
  return o;
}

static jobject mk_iarray(const int* v, jsize n) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 3;
  o->i = malloc(sizeof(jint) * (size_t)n);
  if (v) memcpy(o->i, v, sizeof(jint) * (size_t)n);
  o->len = n;
  return o;
}

static jobject mk_farray(const float* v, jsize n) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 4;
  o->f = malloc(sizeof(jfloat) * (size_t)n);
  if (v) memcpy(o->f, v, sizeof(jfloat) * (size_t)n);
  o->len = n;
  return o;
}

/* ---- JNIEnv implementation --------------------------------------- */
static jclass env_FindClass(JNIEnv* env, const char* name) {
  (void)env;
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 2;
  o->str = name;
  return o;
}

static jint env_ThrowNew(JNIEnv* env, jclass cls, const char* msg) {
  (void)env;
  fprintf(stderr, "java exception %s: %s\n",
          cls ? ((FakeObj*)cls)->str : "?", msg ? msg : "");
  exit(3); /* a real JVM unwinds; the host just fails the test */
}

static const char* env_GetStringUTFChars(JNIEnv* env, jstring s,
                                         jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)s)->str;
}

static void env_ReleaseStringUTFChars(JNIEnv* env, jstring s,
                                      const char* c) {
  (void)env;
  (void)s;
  (void)c;
}

static jsize env_GetArrayLength(JNIEnv* env, jarray a) {
  (void)env;
  return ((FakeObj*)a)->len;
}

static jdoubleArray env_NewDoubleArray(JNIEnv* env, jsize n) {
  (void)env;
  return mk_darray(NULL, n);
}

static jdouble* env_GetDoubleArrayElements(JNIEnv* env, jdoubleArray a,
                                           jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)a)->d;
}

static void env_ReleaseDoubleArrayElements(JNIEnv* env, jdoubleArray a,
                                           jdouble* d, jint mode) {
  (void)env;
  (void)a;
  (void)d;
  (void)mode;
}

static void env_SetDoubleArrayRegion(JNIEnv* env, jdoubleArray a,
                                     jsize start, jsize n,
                                     const jdouble* src) {
  (void)env;
  memcpy(((FakeObj*)a)->d + start, src, sizeof(double) * (size_t)n);
}

static jstring env_NewStringUTF(JNIEnv* env, const char* s) {
  (void)env;
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 0;
  o->str = strdup(s ? s : "");
  return o;
}

static jobjectArray env_NewObjectArray(JNIEnv* env, jsize n, jclass cls,
                                       jobject init) {
  (void)env;
  (void)cls;
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 5;
  o->o = calloc((size_t)(n > 0 ? n : 1), sizeof(jobject));
  for (jsize k = 0; k < n; ++k) o->o[k] = init;
  o->len = n;
  return o;
}

static void env_SetObjectArrayElement(JNIEnv* env, jobjectArray a,
                                      jsize idx, jobject v) {
  (void)env;
  ((FakeObj*)a)->o[idx] = v;
}

static jobject env_GetObjectArrayElement(JNIEnv* env, jobjectArray a,
                                         jsize idx) {
  (void)env;
  return ((FakeObj*)a)->o[idx];
}

static jint* env_GetIntArrayElements(JNIEnv* env, jintArray a,
                                     jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)a)->i;
}

static void env_ReleaseIntArrayElements(JNIEnv* env, jintArray a,
                                        jint* v, jint mode) {
  (void)env;
  (void)a;
  (void)v;
  (void)mode;
}

static jfloat* env_GetFloatArrayElements(JNIEnv* env, jfloatArray a,
                                         jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)a)->f;
}

static void env_ReleaseFloatArrayElements(JNIEnv* env, jfloatArray a,
                                          jfloat* v, jint mode) {
  (void)env;
  (void)a;
  (void)v;
  (void)mode;
}

/* ---- the Java_* entry points under test -------------------------- */
extern jlong Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
    JNIEnv*, jclass, jdoubleArray, jint, jint, jstring);
extern jlong
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMatWithReference(
    JNIEnv*, jclass, jdoubleArray, jint, jint, jstring, jlong);
extern jlong Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromFile(
    JNIEnv*, jclass, jstring, jstring);
extern jlong Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromCSR(
    JNIEnv*, jclass, jintArray, jintArray, jdoubleArray, jint, jstring);
extern jlong Java_com_lightgbm_tpu_LightGBMNative_datasetGetSubset(
    JNIEnv*, jclass, jlong, jintArray, jstring);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
    JNIEnv*, jclass, jlong, jstring, jdoubleArray);
extern jint Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
    JNIEnv*, jclass, jlong);
extern jint Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumFeature(
    JNIEnv*, jclass, jlong);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetSaveBinary(
    JNIEnv*, jclass, jlong, jstring);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetSetFeatureNames(
    JNIEnv*, jclass, jlong, jobjectArray);
extern jobjectArray
Java_com_lightgbm_tpu_LightGBMNative_datasetGetFeatureNames(
    JNIEnv*, jclass, jlong);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetFree(
    JNIEnv*, jclass, jlong);
extern jlong Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
    JNIEnv*, jclass, jlong, jstring);
extern jlong
Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
    JNIEnv*, jclass, jstring);
extern jlong
Java_com_lightgbm_tpu_LightGBMNative_boosterLoadModelFromString(
    JNIEnv*, jclass, jstring);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterAddValidData(
    JNIEnv*, jclass, jlong, jlong);
extern jint Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(
    JNIEnv*, jclass, jlong);
extern jint
Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIterCustom(
    JNIEnv*, jclass, jlong, jfloatArray, jfloatArray);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterRollbackOneIter(
    JNIEnv*, jclass, jlong);
extern jint Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumClasses(
    JNIEnv*, jclass, jlong);
extern jint
Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
    JNIEnv*, jclass, jlong);
extern jint
Java_com_lightgbm_tpu_LightGBMNative_boosterNumberOfTotalModel(
    JNIEnv*, jclass, jlong);
extern jint Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumFeature(
    JNIEnv*, jclass, jlong);
extern jobjectArray
Java_com_lightgbm_tpu_LightGBMNative_boosterGetFeatureNames(
    JNIEnv*, jclass, jlong);
extern jint Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalCounts(
    JNIEnv*, jclass, jlong);
extern jobjectArray
Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalNames(
    JNIEnv*, jclass, jlong);
extern jdoubleArray Java_com_lightgbm_tpu_LightGBMNative_boosterGetEval(
    JNIEnv*, jclass, jlong, jint);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterResetParameter(
    JNIEnv*, jclass, jlong, jstring);
extern void
Java_com_lightgbm_tpu_LightGBMNative_boosterResetTrainingData(
    JNIEnv*, jclass, jlong, jlong);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterMerge(
    JNIEnv*, jclass, jlong, jlong);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
    JNIEnv*, jclass, jlong, jint, jstring);
extern jstring
Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModelToString(
    JNIEnv*, jclass, jlong, jint);
extern jstring Java_com_lightgbm_tpu_LightGBMNative_boosterDumpModel(
    JNIEnv*, jclass, jlong, jint);
extern jdoubleArray
Java_com_lightgbm_tpu_LightGBMNative_boosterFeatureImportance(
    JNIEnv*, jclass, jlong, jint, jint);
extern jlong
Java_com_lightgbm_tpu_LightGBMNative_boosterCalcNumPredict(
    JNIEnv*, jclass, jlong, jint, jint, jint);
extern jdouble
Java_com_lightgbm_tpu_LightGBMNative_boosterGetLeafValue(
    JNIEnv*, jclass, jlong, jint, jint);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterSetLeafValue(
    JNIEnv*, jclass, jlong, jint, jint, jdouble);
extern jdoubleArray
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
    JNIEnv*, jclass, jlong, jdoubleArray, jint, jint, jint, jint);
extern jdoubleArray
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForCSR(
    JNIEnv*, jclass, jlong, jintArray, jintArray, jdoubleArray, jint,
    jint, jint);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForFile(
    JNIEnv*, jclass, jlong, jstring, jint, jint, jint, jstring);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterFree(
    JNIEnv*, jclass, jlong);

static unsigned long rng_state = 777;
static double frand(void) {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return (double)(rng_state % 1000000ul) / 1000000.0 - 0.5;
}

/* verdicts leave through _exit: the embedded CPython + jax thread
 * pools make glibc DSO-destructor order hostile after main returns
 * (same post-main SIGSEGV class the R stub host hit once multiple
 * boosters existed) */
#define CHECK(cond, code, msg)                        \
  do {                                                \
    if (!(cond)) {                                    \
      fprintf(stderr, "FAIL(%d): %s\n", code, msg);  \
      fflush(NULL);                                   \
      _exit(code);                                    \
    }                                                 \
  } while (0)

int main(int argc, char** argv) {
  const char* model_path = argc > 1 ? argv[1] : "/tmp/jni_model.txt";
  char path2[1024];
  struct JNINativeInterface_ table = {
      .FindClass = env_FindClass,
      .ThrowNew = env_ThrowNew,
      .GetStringUTFChars = env_GetStringUTFChars,
      .ReleaseStringUTFChars = env_ReleaseStringUTFChars,
      .GetArrayLength = env_GetArrayLength,
      .NewDoubleArray = env_NewDoubleArray,
      .GetDoubleArrayElements = env_GetDoubleArrayElements,
      .ReleaseDoubleArrayElements = env_ReleaseDoubleArrayElements,
      .SetDoubleArrayRegion = env_SetDoubleArrayRegion,
      .NewStringUTF = env_NewStringUTF,
      .NewObjectArray = env_NewObjectArray,
      .SetObjectArrayElement = env_SetObjectArrayElement,
      .GetObjectArrayElement = env_GetObjectArrayElement,
      .GetIntArrayElements = env_GetIntArrayElements,
      .ReleaseIntArrayElements = env_ReleaseIntArrayElements,
      .GetFloatArrayElements = env_GetFloatArrayElements,
      .ReleaseFloatArrayElements = env_ReleaseFloatArrayElements,
  };
  JNIEnv env_obj = &table;
  JNIEnv* env = &env_obj;

  const int n = 500, f = 4, nv = 150;
  double* mat = malloc(sizeof(double) * n * f); /* row-major (Java) */
  double* label = malloc(sizeof(double) * n);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) mat[i * f + j] = frand();
    label[i] = (mat[i * f] + 0.5 * mat[i * f + 1] > 0.0) ? 1.0 : 0.0;
  }
  double* vmat = malloc(sizeof(double) * nv * f);
  double* vlabel = malloc(sizeof(double) * nv);
  for (int i = 0; i < nv; ++i) {
    for (int j = 0; j < f; ++j) vmat[i * f + j] = frand();
    vlabel[i] = (vmat[i * f] + 0.5 * vmat[i * f + 1] > 0.0) ? 1.0 : 0.0;
  }

  jdoubleArray j_mat = mk_darray(mat, n * f);
  jstring params = mk_string(
      "objective=binary verbose=-1 num_leaves=15 min_data_in_leaf=5");
  jlong ds = Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
      env, NULL, j_mat, n, f, params);
  Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
      env, NULL, ds, mk_string("label"), mk_darray(label, n));
  jlong bst = Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
      env, NULL, ds, params);
  for (int it = 0; it < 20; ++it)
    Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(env, NULL,
                                                              bst);
  jdoubleArray pred =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
          env, NULL, bst, j_mat, n, f, 0, -1);
  CHECK(env_GetArrayLength(env, pred) == n, 4, "prediction length");
  double* p = env_GetDoubleArrayElements(env, pred, NULL);
  int correct = 0;
  for (int i = 0; i < n; ++i)
    correct += ((p[i] > 0.5) == (label[i] > 0.5));
  double acc = (double)correct / n;

  Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
      env, NULL, bst, -1, mk_string(model_path));
  jlong bst2 =
      Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
          env, NULL, mk_string(model_path));
  jdoubleArray pred2 =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
          env, NULL, bst2, j_mat, n, f, 0, -1);
  double* p2 = env_GetDoubleArrayElements(env, pred2, NULL);
  double maxdiff = 0.0;
  for (int i = 0; i < n; ++i) {
    double d = fabs(p[i] - p2[i]);
    if (d > maxdiff) maxdiff = d;
  }
  CHECK(acc >= 0.85, 5, "training accuracy");
  CHECK(maxdiff <= 1e-10, 6, "save/reload parity");

  /* ---- getters ---------------------------------------------------- */
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
            env, NULL, ds) == n, 10, "datasetGetNumData");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumFeature(
            env, NULL, ds) == f, 11, "datasetGetNumFeature");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumClasses(
            env, NULL, bst) == 1, 12, "boosterGetNumClasses");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumFeature(
            env, NULL, bst) == f, 13, "boosterGetNumFeature");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterNumberOfTotalModel(
            env, NULL, bst) == 20, 14, "boosterNumberOfTotalModel");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
            env, NULL, bst) == 20, 15, "boosterGetCurrentIteration");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterCalcNumPredict(
            env, NULL, bst, n, 0, -1) == n, 16, "boosterCalcNumPredict");

  /* ---- CSR predict parity (all entries explicit) ------------------ */
  int* indptr = malloc(sizeof(int) * (n + 1));
  int* indices = malloc(sizeof(int) * n * f);
  for (int i = 0; i <= n; ++i) indptr[i] = i * f;
  for (int i = 0; i < n; ++i)
    for (int j = 0; j < f; ++j) indices[i * f + j] = j;
  jintArray j_indptr = mk_iarray(indptr, n + 1);
  jintArray j_indices = mk_iarray(indices, n * f);
  jdoubleArray pred_csr =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForCSR(
          env, NULL, bst, j_indptr, j_indices, j_mat, f, 0, -1);
  CHECK(env_GetArrayLength(env, pred_csr) == n, 17, "CSR pred length");
  double* pc = env_GetDoubleArrayElements(env, pred_csr, NULL);
  double csr_diff = 0.0;
  for (int i = 0; i < n; ++i) {
    double d = fabs(p[i] - pc[i]);
    if (d > csr_diff) csr_diff = d;
  }
  CHECK(csr_diff <= 1e-10, 18, "CSR vs dense predict parity");

  /* ---- CSR dataset trains ----------------------------------------- */
  jlong ds_csr =
      Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromCSR(
          env, NULL, j_indptr, j_indices, j_mat, f, params);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
            env, NULL, ds_csr) == n, 19, "CSR dataset rows");
  Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
      env, NULL, ds_csr, mk_string("label"), mk_darray(label, n));
  jlong bst_csr = Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
      env, NULL, ds_csr, params);
  for (int it = 0; it < 3; ++it)
    Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(
        env, NULL, bst_csr);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
            env, NULL, bst_csr) == 3, 20, "CSR booster trained");

  /* ---- valid-set eval flow ---------------------------------------- */
  jdoubleArray j_vmat = mk_darray(vmat, nv * f);
  jlong dsv = Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMatWithReference(
      env, NULL, j_vmat, nv, f, params, ds);
  Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
      env, NULL, dsv, mk_string("label"), mk_darray(vlabel, nv));
  jlong bst_e = Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
      env, NULL, ds, params);
  Java_com_lightgbm_tpu_LightGBMNative_boosterAddValidData(
      env, NULL, bst_e, dsv);
  for (int it = 0; it < 3; ++it)
    Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(
        env, NULL, bst_e);
  int ev_counts = Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalCounts(
      env, NULL, bst_e);
  CHECK(ev_counts >= 1, 21, "eval counts");
  jobjectArray ev_names =
      Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalNames(
          env, NULL, bst_e);
  CHECK(env_GetArrayLength(env, ev_names) == ev_counts, 22,
        "eval names count");
  const char* ev0_name = env_GetStringUTFChars(
      env, env_GetObjectArrayElement(env, ev_names, 0), NULL);
  CHECK(strlen(ev0_name) > 0, 23, "eval name nonempty");
  for (int di = 0; di <= 1; ++di) {
    jdoubleArray ev = Java_com_lightgbm_tpu_LightGBMNative_boosterGetEval(
        env, NULL, bst_e, di);
    jsize ne = env_GetArrayLength(env, ev);
    CHECK(ne == ev_counts, 24, "eval values count");
    double* evv = env_GetDoubleArrayElements(env, ev, NULL);
    for (jsize k = 0; k < ne; ++k)
      CHECK(evv[k] == evv[k], 25, "eval value is NaN");
  }

  /* ---- custom-objective iteration + rollback ----------------------- */
  jdoubleArray pe = Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
      env, NULL, bst_e, j_mat, n, f, 0, -1);
  double* pev = env_GetDoubleArrayElements(env, pe, NULL);
  float* grad = malloc(sizeof(float) * n);
  float* hess = malloc(sizeof(float) * n);
  for (int i = 0; i < n; ++i) {
    grad[i] = (float)(pev[i] - label[i]);
    hess[i] = (float)(pev[i] * (1.0 - pev[i]) + 1e-6);
  }
  Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIterCustom(
      env, NULL, bst_e, mk_farray(grad, n), mk_farray(hess, n));
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
            env, NULL, bst_e) == 4, 26, "custom iter advanced");
  Java_com_lightgbm_tpu_LightGBMNative_boosterRollbackOneIter(
      env, NULL, bst_e);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
            env, NULL, bst_e) == 3, 27, "rollback");
  Java_com_lightgbm_tpu_LightGBMNative_boosterResetParameter(
      env, NULL, bst_e, mk_string("learning_rate=0.05"));

  /* ---- model string round trip + dump ------------------------------ */
  jstring mstr = Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModelToString(
      env, NULL, bst, -1);
  const char* mtxt = env_GetStringUTFChars(env, mstr, NULL);
  CHECK(strlen(mtxt) > 100, 28, "model string length");
  jlong bst3 = Java_com_lightgbm_tpu_LightGBMNative_boosterLoadModelFromString(
      env, NULL, mstr);
  jdoubleArray pred3 =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
          env, NULL, bst3, j_mat, n, f, 0, -1);
  double* p3 = env_GetDoubleArrayElements(env, pred3, NULL);
  double sdiff = 0.0;
  for (int i = 0; i < n; ++i) {
    double d = fabs(p[i] - p3[i]);
    if (d > sdiff) sdiff = d;
  }
  CHECK(sdiff <= 1e-10, 29, "string save/load parity");
  jstring dump = Java_com_lightgbm_tpu_LightGBMNative_boosterDumpModel(
      env, NULL, bst, -1);
  const char* dtxt = env_GetStringUTFChars(env, dump, NULL);
  CHECK(strstr(dtxt, "tree") != NULL, 30, "dump contains trees");

  /* ---- importance, leaf mutation, merge ---------------------------- */
  jdoubleArray imp =
      Java_com_lightgbm_tpu_LightGBMNative_boosterFeatureImportance(
          env, NULL, bst, -1, 0);
  CHECK(env_GetArrayLength(env, imp) == f, 31, "importance length");
  double* iv = env_GetDoubleArrayElements(env, imp, NULL);
  double isum = 0.0;
  for (int j = 0; j < f; ++j) isum += iv[j];
  CHECK(isum > 0.0, 32, "importance sum");

  double leaf0 = Java_com_lightgbm_tpu_LightGBMNative_boosterGetLeafValue(
      env, NULL, bst3, 0, 0);
  Java_com_lightgbm_tpu_LightGBMNative_boosterSetLeafValue(
      env, NULL, bst3, 0, 0, leaf0 + 0.5);
  double leaf1 = Java_com_lightgbm_tpu_LightGBMNative_boosterGetLeafValue(
      env, NULL, bst3, 0, 0);
  CHECK(fabs(leaf1 - (leaf0 + 0.5)) < 1e-12, 33, "leaf set/get");

  jlong bst4 = Java_com_lightgbm_tpu_LightGBMNative_boosterLoadModelFromString(
      env, NULL, mstr);
  Java_com_lightgbm_tpu_LightGBMNative_boosterMerge(env, NULL, bst4,
                                                    bst);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterNumberOfTotalModel(
            env, NULL, bst4) == 40, 34, "merge tree count");

  /* ---- subset, binary save, feature names -------------------------- */
  int subrows[100];
  for (int i = 0; i < 100; ++i) subrows[i] = i;
  jlong sub = Java_com_lightgbm_tpu_LightGBMNative_datasetGetSubset(
      env, NULL, ds, mk_iarray(subrows, 100), mk_string(""));
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
            env, NULL, sub) == 100, 35, "subset rows");
  snprintf(path2, sizeof(path2), "%s.dsbin", model_path);
  Java_com_lightgbm_tpu_LightGBMNative_datasetSaveBinary(
      env, NULL, ds, mk_string(path2));
  FILE* fh = fopen(path2, "rb");
  CHECK(fh != NULL, 36, "dataset binary saved");
  fclose(fh);

  jobjectArray names = env_NewObjectArray(env, f, NULL, NULL);
  env_SetObjectArrayElement(env, names, 0, mk_string("fa"));
  env_SetObjectArrayElement(env, names, 1, mk_string("fb"));
  env_SetObjectArrayElement(env, names, 2, mk_string("fc"));
  env_SetObjectArrayElement(env, names, 3, mk_string("fd"));
  Java_com_lightgbm_tpu_LightGBMNative_datasetSetFeatureNames(
      env, NULL, ds, names);
  jobjectArray got =
      Java_com_lightgbm_tpu_LightGBMNative_datasetGetFeatureNames(
          env, NULL, ds);
  CHECK(env_GetArrayLength(env, got) == f, 37, "feature names count");
  const char* fc = env_GetStringUTFChars(
      env, env_GetObjectArrayElement(env, got, 2), NULL);
  CHECK(strcmp(fc, "fc") == 0, 38, "feature name round trip");
  jobjectArray bnames =
      Java_com_lightgbm_tpu_LightGBMNative_boosterGetFeatureNames(
          env, NULL, bst);
  CHECK(env_GetArrayLength(env, bnames) == f, 39,
        "booster feature names count");

  /* ---- file prediction --------------------------------------------- */
  snprintf(path2, sizeof(path2), "%s.pred_in.csv", model_path);
  FILE* pf = fopen(path2, "w");
  CHECK(pf != NULL, 40, "predict input open");
  for (int i = 0; i < nv; ++i) {
    fprintf(pf, "%g", vlabel[i]);
    for (int j = 0; j < f; ++j) fprintf(pf, ",%g", vmat[i * f + j]);
    fprintf(pf, "\n");
  }
  fclose(pf);
  char rpath[1024];
  snprintf(rpath, sizeof(rpath), "%s.pred_out.txt", model_path);
  Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForFile(
      env, NULL, bst, mk_string(path2), 0, 0, -1, mk_string(rpath));
  FILE* rf = fopen(rpath, "r");
  CHECK(rf != NULL, 41, "predict output exists");
  int lines = 0, ch;
  while ((ch = fgetc(rf)) != EOF)
    if (ch == '\n') ++lines;
  fclose(rf);
  CHECK(lines == nv, 42, "predict output rows");

  /* ---- dataset from text file + training-data swap ----------------- */
  jlong ds_file =
      Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromFile(
          env, NULL, mk_string(path2), params);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
            env, NULL, ds_file) == nv, 43, "file dataset rows");
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumFeature(
            env, NULL, ds_file) == f, 44, "file dataset features");
  Java_com_lightgbm_tpu_LightGBMNative_boosterResetTrainingData(
      env, NULL, bst_e, ds_file);
  Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(env, NULL,
                                                            bst_e);
  CHECK(Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
            env, NULL, bst_e) == 4, 45, "trains on swapped data");
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, ds_file);

  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst2);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst3);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst4);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst_e);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst_csr);
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, sub);
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, dsv);
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, ds_csr);
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, ds);
  printf("JNI-HOST OK acc=%.3f maxdiff=%g\n", acc, maxdiff);
  fflush(NULL);
  _exit(0);
}
