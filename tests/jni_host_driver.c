/* C host that EXECUTES the JNI binding (jni/lightgbm_jni.c) without a
 * JVM: fabricates a JNIEnv function table (string/array accessors,
 * exception raise) and drives dataset -> train -> predict -> save ->
 * reload -> parity through the Java_* entry points against the real
 * liblgbm_tpu.so.  With a JDK present the same binding builds against
 * the genuine <jni.h> and runs under a real JVM (see
 * jni/LightGBMNative.java). */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../jni/jni_min.h"

/* ---- fake object model ------------------------------------------- */
typedef struct _jobject {
  int kind; /* 0 = string, 1 = double array, 2 = class */
  const char* str;
  double* d;
  jsize len;
} FakeObj;

static jobject mk_string(const char* s) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 0;
  o->str = s;
  return o;
}

static jobject mk_darray(const double* v, jsize n) {
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 1;
  o->d = malloc(sizeof(double) * (size_t)n);
  if (v) memcpy(o->d, v, sizeof(double) * (size_t)n);
  o->len = n;
  return o;
}

/* ---- JNIEnv implementation --------------------------------------- */
static jclass env_FindClass(JNIEnv* env, const char* name) {
  (void)env;
  FakeObj* o = calloc(1, sizeof(FakeObj));
  o->kind = 2;
  o->str = name;
  return o;
}

static jint env_ThrowNew(JNIEnv* env, jclass cls, const char* msg) {
  (void)env;
  fprintf(stderr, "java exception %s: %s\n",
          cls ? ((FakeObj*)cls)->str : "?", msg ? msg : "");
  exit(3); /* a real JVM unwinds; the host just fails the test */
}

static const char* env_GetStringUTFChars(JNIEnv* env, jstring s,
                                         jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)s)->str;
}

static void env_ReleaseStringUTFChars(JNIEnv* env, jstring s,
                                      const char* c) {
  (void)env;
  (void)s;
  (void)c;
}

static jsize env_GetArrayLength(JNIEnv* env, jarray a) {
  (void)env;
  return ((FakeObj*)a)->len;
}

static jdoubleArray env_NewDoubleArray(JNIEnv* env, jsize n) {
  (void)env;
  return mk_darray(NULL, n);
}

static jdouble* env_GetDoubleArrayElements(JNIEnv* env, jdoubleArray a,
                                           jboolean* copy) {
  (void)env;
  if (copy) *copy = 0;
  return ((FakeObj*)a)->d;
}

static void env_ReleaseDoubleArrayElements(JNIEnv* env, jdoubleArray a,
                                           jdouble* d, jint mode) {
  (void)env;
  (void)a;
  (void)d;
  (void)mode;
}

static void env_SetDoubleArrayRegion(JNIEnv* env, jdoubleArray a,
                                     jsize start, jsize n,
                                     const jdouble* src) {
  (void)env;
  memcpy(((FakeObj*)a)->d + start, src, sizeof(double) * (size_t)n);
}

/* ---- the Java_* entry points under test -------------------------- */
extern jlong Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
    JNIEnv*, jclass, jdoubleArray, jint, jint, jstring);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
    JNIEnv*, jclass, jlong, jstring, jdoubleArray);
extern void Java_com_lightgbm_tpu_LightGBMNative_datasetFree(
    JNIEnv*, jclass, jlong);
extern jlong Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
    JNIEnv*, jclass, jlong, jstring);
extern jlong
Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
    JNIEnv*, jclass, jstring);
extern jint Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(
    JNIEnv*, jclass, jlong);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
    JNIEnv*, jclass, jlong, jint, jstring);
extern jdoubleArray
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
    JNIEnv*, jclass, jlong, jdoubleArray, jint, jint, jint, jint);
extern void Java_com_lightgbm_tpu_LightGBMNative_boosterFree(
    JNIEnv*, jclass, jlong);

static unsigned long rng_state = 777;
static double frand(void) {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return (double)(rng_state % 1000000ul) / 1000000.0 - 0.5;
}

int main(int argc, char** argv) {
  const char* model_path = argc > 1 ? argv[1] : "/tmp/jni_model.txt";
  struct JNINativeInterface_ table = {
      env_FindClass,
      env_ThrowNew,
      env_GetStringUTFChars,
      env_ReleaseStringUTFChars,
      env_GetArrayLength,
      env_NewDoubleArray,
      env_GetDoubleArrayElements,
      env_ReleaseDoubleArrayElements,
      env_SetDoubleArrayRegion,
  };
  JNIEnv env_obj = &table;
  JNIEnv* env = &env_obj;

  const int n = 500, f = 4;
  double* mat = malloc(sizeof(double) * n * f); /* row-major (Java) */
  double* label = malloc(sizeof(double) * n);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) mat[i * f + j] = frand();
    label[i] = (mat[i * f] + 0.5 * mat[i * f + 1] > 0.0) ? 1.0 : 0.0;
  }

  jdoubleArray j_mat = mk_darray(mat, n * f);
  jstring params = mk_string(
      "objective=binary verbose=-1 num_leaves=15 min_data_in_leaf=5");
  jlong ds = Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
      env, NULL, j_mat, n, f, params);
  Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
      env, NULL, ds, mk_string("label"), mk_darray(label, n));
  jlong bst = Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(
      env, NULL, ds, params);
  for (int it = 0; it < 20; ++it)
    Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(env, NULL,
                                                              bst);
  jdoubleArray pred =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
          env, NULL, bst, j_mat, n, f, 0, -1);
  if (env_GetArrayLength(env, pred) != n) {
    fprintf(stderr, "bad prediction length\n");
    return 4;
  }
  double* p = env_GetDoubleArrayElements(env, pred, NULL);
  int correct = 0;
  for (int i = 0; i < n; ++i)
    correct += ((p[i] > 0.5) == (label[i] > 0.5));
  double acc = (double)correct / n;

  Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
      env, NULL, bst, -1, mk_string(model_path));
  jlong bst2 =
      Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
          env, NULL, mk_string(model_path));
  jdoubleArray pred2 =
      Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
          env, NULL, bst2, j_mat, n, f, 0, -1);
  double* p2 = env_GetDoubleArrayElements(env, pred2, NULL);
  double maxdiff = 0.0;
  for (int i = 0; i < n; ++i) {
    double d = fabs(p[i] - p2[i]);
    if (d > maxdiff) maxdiff = d;
  }
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst);
  Java_com_lightgbm_tpu_LightGBMNative_boosterFree(env, NULL, bst2);
  Java_com_lightgbm_tpu_LightGBMNative_datasetFree(env, NULL, ds);
  printf("JNI-HOST OK acc=%.3f maxdiff=%g\n", acc, maxdiff);
  if (acc < 0.85) return 5;
  if (maxdiff > 1e-10) return 6;
  return 0;
}
