"""Mesh-sharded dataset subsystem (lightgbm_tpu/sharded/ — round 16).

Pins, per the acceptance criteria:

- distributed bin finding: merged mappers BYTE-EQUAL to a single-host
  fit on the concatenated data (dense / categorical / NaN /
  zero-as-missing corners, EFB bundles included), identical at every
  shard count, candidates crossing the instrumented collective seam;
- ShardedDataset training: byte-identical trees across 1/2/4-shard
  construction vs the single-matrix route — serial, the quantized
  Pallas interpret seam, and a data-parallel mesh with per-device
  placed shards;
- shard-cache v2: zero-copy reload parity, loud refusal of a wrong
  world size / stale mapper fingerprint / truncated shard file, and a
  SIGKILL during shard ingest leaving the manifest uncorrupted
  (resume = reconstruct; the committed cache stays loadable).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.sharded import (ShardCacheError, ShardedDataset,
                                  collect_candidates, load_shard_cache,
                                  mapper_fingerprint, merge_candidates,
                                  save_shard_cache, shard_row_ranges)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = {"objective": "binary", "verbose": -1, "num_leaves": 7,
          "max_bin": 31, "min_data_in_leaf": 5}


def _corner_data(n=600, f=8, seed=0):
    """Dense + sparse-ish + NaN + categorical columns — the bin-mapper
    corner set."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.25] = 0.0          # zeros stay implicit
    X[rng.rand(n, f) < 0.05] = np.nan       # MISSING_NAN routing
    X[:, 3] = rng.randint(0, 7, n)          # categorical
    X[:, 5] = np.round(X[:, 5])             # few distinct values
    y = (np.nan_to_num(X[:, 0]) - 0.5 * np.nan_to_num(X[:, 1])
         > 0).astype(float)
    return X, y


def _cfg(**over):
    return Config.from_params(dict(PARAMS, **over))


# ---------------------------------------------------------------------------
# distributed bin finding
# ---------------------------------------------------------------------------
def test_row_ranges_disjoint_cover():
    for n, s in ((10, 3), (1000, 4), (7, 7), (5, 1)):
        rr = shard_row_ranges(n, s)
        assert rr[0][0] == 0 and rr[-1][1] == n
        assert all(rr[i][1] == rr[i + 1][0] for i in range(s - 1))


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_merged_mappers_byte_equal_single_host(shards):
    """The acceptance pin: distributed bin finding over disjoint row
    ranges fits mappers BYTE-EQUAL to the single-host fit on the
    concatenated data — dense/categorical/NaN corners, EFB bundle
    layout included."""
    X, y = _corner_data()
    cfg = _cfg()
    single = lgb.Dataset(X, label=y,
                         categorical_feature=[3]).construct(cfg)
    sds = ShardedDataset.construct_sharded(
        X, label=y, config=_cfg(), num_shards=shards,
        categorical_features=[3])
    assert sds.feature_infos() == single.feature_infos()
    assert mapper_fingerprint(sds.mappers, sds._bundles, sds.max_bin) \
        == mapper_fingerprint(single.mappers, single._bundles,
                              single.max_bin)
    # per-mapper byte equality, not just the digest
    for ms, mh in zip(sds.mappers, single.mappers):
        np.testing.assert_array_equal(
            np.asarray(ms.bin_upper_bound, dtype=np.float64),
            np.asarray(mh.bin_upper_bound, dtype=np.float64))
        assert ms.num_bin == mh.num_bin
        assert ms.missing_type == mh.missing_type
        assert ms.default_bin == mh.default_bin
        assert getattr(ms, "categorical_2_bin", {}) \
            == getattr(mh, "categorical_2_bin", {})
    # and the packed shards reassemble to the single matrix
    assert np.array_equal(sds.assembled_group_bins(),
                          np.asarray(single.group_bins))


def test_merged_mappers_zero_as_missing_corner():
    X, y = _corner_data(seed=3)
    cfg = _cfg(zero_as_missing=True)
    single = lgb.Dataset(X, label=y).construct(cfg)
    sds = ShardedDataset.construct_sharded(
        X, label=y, config=_cfg(zero_as_missing=True), num_shards=3)
    assert sds.feature_infos() == single.feature_infos()
    assert np.array_equal(sds.assembled_group_bins(),
                          np.asarray(single.group_bins))


def test_candidates_cross_instrumented_collective_seam():
    """Boundary candidates must ride the counted allgather seam: the
    merge bumps collective_allgather_calls/bytes like every other
    explicit host collective (docs/OBSERVABILITY.md)."""
    from lightgbm_tpu.telemetry import TELEMETRY
    X, _ = _corner_data(n=200)
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    try:
        cands = [collect_candidates(X[a:b], _cfg(), rank=i, world=2)
                 for i, (a, b) in enumerate(shard_row_ranges(200, 2))]
        vals, rows, total = merge_candidates(cands)
        assert total == 200
        c = TELEMETRY.counters()
        assert c.get("collective_allgather_calls", 0) > 0
        assert c.get("collective_allgather_bytes", 0) > 0
    finally:
        TELEMETRY.configure("off")
        TELEMETRY.reset()


def test_merge_is_rank_deterministic():
    """Rank order decides the merged layout, not list order."""
    X, _ = _corner_data(n=300, seed=5)
    rr = shard_row_ranges(300, 3)
    cands = [collect_candidates(X[a:b], _cfg(), rank=i, world=3)
             for i, (a, b) in enumerate(rr)]
    v1, r1, t1 = merge_candidates(cands)
    v2, r2, t2 = merge_candidates(list(reversed(cands)))
    assert t1 == t2
    for a, b in zip(v1, v2):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)


def test_binfind_fault_seam_registered():
    from lightgbm_tpu.reliability.faults import SEAMS, FAULTS
    assert "sharded.binfind" in SEAMS
    assert "sharded.ingest" in SEAMS
    FAULTS.configure("sharded.binfind:1:RuntimeError")
    try:
        X, y = _corner_data(n=64)
        with pytest.raises(RuntimeError):
            ShardedDataset.construct_sharded(X, label=y, config=_cfg(),
                                             num_shards=2)
    finally:
        FAULTS.reset()


# ---------------------------------------------------------------------------
# byte-identical trees across shard counts and routes
# ---------------------------------------------------------------------------
def _model_from(core_or_ds, **over):
    bst = lgb.train(dict(PARAMS, **over), core_or_ds, 6,
                    verbose_eval=False)
    return bst.model_to_string()


@pytest.fixture(scope="module")
def parity_data():
    X, y = _corner_data(n=800, seed=11)
    ref = _model_from(lgb.Dataset(X, label=y, categorical_feature=[3]))
    return X, y, ref


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_trees_byte_identical_vs_single_matrix(parity_data, shards):
    X, y, ref = parity_data
    sds = ShardedDataset.construct_sharded(
        X, label=y, config=_cfg(), num_shards=shards,
        categorical_features=[3])
    assert _model_from(sds) == ref, (
        f"{shards}-shard construction changed the trained trees")


def test_trees_byte_identical_on_interpret_seam():
    """The quantized Pallas interpret seam (the container-side stand-in
    for the real chip, test_packed_carry idiom): the sharded route
    must feed it byte-identical bins and grow byte-identical trees."""
    X, y = _corner_data(n=256, f=6, seed=7)
    over = {"quantized_grad": True, "hist_compute_dtype": "bfloat16",
            "force_pallas_interpret": True, "max_bin": 63,
            "min_data_in_leaf": 2}
    ref = _model_from(lgb.Dataset(X, label=y), **over)
    sds = ShardedDataset.construct_sharded(X, label=y,
                                           config=_cfg(**over),
                                           num_shards=2)
    assert _model_from(sds, **over) == ref


@pytest.mark.skipif("len(__import__('jax').devices()) < 4",
                    reason="needs the 8-virtual-device CPU mesh")
def test_mesh_per_device_shard_placement_and_tree_parity():
    """Data-parallel mesh: the sharded route places one bin shard per
    device (genuinely different row blocks, assembled zero-host-concat)
    and trains byte-identical trees to the single-matrix route under
    the SAME mesh."""
    import jax

    from lightgbm_tpu.boosting.gbdt import GBDT
    rng = np.random.RandomState(2)
    n = 4096 * 4
    X = rng.randn(n, 6)
    y = (X[:, 0] > 0).astype(float)
    mesh_over = {"tree_learner": "data", "mesh_shape": (4,),
                 "mesh_axes": ("data",), "min_data_in_leaf": 2}

    cfg1 = _cfg(**mesh_over)
    g1 = GBDT(cfg1, lgb.Dataset(X, label=y).construct(cfg1))
    cfg2 = _cfg(**mesh_over)
    sds = ShardedDataset.construct_sharded(X, label=y, config=cfg2,
                                           num_shards=4)
    g2 = GBDT(cfg2, sds)

    shards = g2.grower.bins.addressable_shards
    assert len(shards) == 4
    assert len({np.asarray(s.data).tobytes() for s in shards}) > 1, \
        "row shards identical — bins not genuinely sharded"
    assert sum(np.asarray(s.data).shape[0] for s in shards) \
        == g2.grower.n_padded
    # the logical global layout matches the single-matrix route, so
    # the two placed arrays are element-equal
    whole = sds.assembled_group_bins()
    for s in shards:
        lo = s.index[0].start or 0
        stop = s.index[0].stop
        blk = np.asarray(s.data)
        valid = max(0, min(len(whole) - lo, blk.shape[0]))
        assert np.array_equal(blk[:valid], whole[lo:lo + valid])
        assert not blk[valid:].any()        # zero tail pad

    for _ in range(2):
        g1.train_one_iter()
        g2.train_one_iter()
    g1.flush_models(final=True)
    g2.flush_models(final=True)
    m1 = "".join(t.to_string() for t in g1.models)
    m2 = "".join(t.to_string() for t in g2.models)
    assert m1 == m2, "sharded-construct trees diverged under the mesh"


def test_valid_set_aligns_to_sharded_reference(parity_data):
    """Validation data must bin against the sharded training set's
    merged mappers exactly like it aligns to a single-matrix core."""
    X, y, _ = parity_data
    sds = ShardedDataset.construct_sharded(
        X, label=y, config=_cfg(), num_shards=2,
        categorical_features=[3])
    er = {}
    bst = lgb.train(dict(PARAMS), sds, 4,
                    valid_sets=[lgb.Dataset(X[:200], label=y[:200],
                                            reference=sds)],
                    evals_result=er, verbose_eval=False)
    assert bst.num_trees() == 4
    assert er and "valid_0" in er


# ---------------------------------------------------------------------------
# shard cache v2
# ---------------------------------------------------------------------------
@pytest.fixture()
def cached(tmp_path):
    X, y = _corner_data(n=400, seed=13)
    sds = ShardedDataset.construct_sharded(X, label=y, config=_cfg(),
                                           num_shards=3)
    d = str(tmp_path / "cache")
    save_shard_cache(sds, d)
    return X, y, sds, d


def test_shard_cache_roundtrip_zero_copy(cached):
    X, y, sds, d = cached
    re = load_shard_cache(d, expect_world_size=3)
    assert re.world_size == 3
    assert re.shard_ranges == sds.shard_ranges
    assert isinstance(re.shard_bins[0], np.memmap), \
        "reload must memmap the shard bin sections (zero-copy)"
    assert np.array_equal(re.assembled_group_bins(),
                          sds.assembled_group_bins())
    np.testing.assert_array_equal(re.metadata.label,
                                  sds.metadata.label)
    # a model trained from the reloaded cache is byte-identical
    assert _model_from(re) == _model_from(sds)


def test_shard_cache_rejects_wrong_world_size(cached):
    _, _, _, d = cached
    with pytest.raises(ShardCacheError, match="world size"):
        load_shard_cache(d, expect_world_size=2)


def test_shard_cache_rejects_stale_fingerprint(cached):
    _, _, _, d = cached
    mpath = os.path.join(d, "manifest.json")
    man = json.load(open(mpath))
    man["mapper_fingerprint"] = "0" * 64
    # a STALE-but-well-formed manifest: re-stamp the self-digest so
    # the fingerprint check (not the torn-write digest) must fire
    from lightgbm_tpu.sharded.cache import _manifest_crc
    man["manifest_crc"] = _manifest_crc(man)
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(ShardCacheError, match="fingerprint"):
        load_shard_cache(d, expect_world_size=3)


def test_shard_cache_rejects_truncated_shard(cached):
    _, _, _, d = cached
    p = os.path.join(d, "shard_1.bin")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 64)
    with pytest.raises(ShardCacheError, match="truncated"):
        load_shard_cache(d, expect_world_size=3)


def test_shard_cache_rejects_missing_manifest(tmp_path):
    with pytest.raises(ShardCacheError, match="manifest"):
        load_shard_cache(str(tmp_path), expect_world_size=2)


def test_basic_dataset_routes_through_cache(tmp_path):
    """The lazy Dataset front door: sharded_shards arms the sharded
    route, sharded_cache_dir persists it, and a second construct
    reloads the cache instead of re-binning (and refuses a changed
    world size loudly)."""
    X, y = _corner_data(n=300, seed=17)
    d = str(tmp_path / "c")
    over = {"sharded_shards": 2, "sharded_cache_dir": d}
    core = lgb.Dataset(X, label=y,
                       params=dict(PARAMS, **over)).construct()
    assert isinstance(core, ShardedDataset) and core.world_size == 2
    assert os.path.isfile(os.path.join(d, "manifest.json"))
    re = lgb.Dataset(X, label=y,
                     params=dict(PARAMS, **over)).construct()
    assert isinstance(re, ShardedDataset)
    assert np.array_equal(re.assembled_group_bins(),
                          core.assembled_group_bins())
    with pytest.raises(ShardCacheError, match="world size"):
        lgb.Dataset(X, label=y, params=dict(
            PARAMS, sharded_shards=4,
            sharded_cache_dir=d)).construct()


# ---------------------------------------------------------------------------
# kill during shard ingest: the manifest survives
# ---------------------------------------------------------------------------
_KILL_CHILD = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from lightgbm_tpu.config import Config
from lightgbm_tpu.sharded import ShardedDataset, save_shard_cache
rng = np.random.RandomState(13)
X = rng.randn(400, 8); X[rng.rand(400, 8) < 0.25] = 0.0
y = (X[:, 0] > 0).astype(float)
cfg = Config.from_params({"objective": "binary", "verbose": -1,
                          "max_bin": 31,
                          "fault_plan": os.environ.get("PLAN", "")})
sds = ShardedDataset.construct_sharded(X, label=y, config=cfg,
                                       num_shards=3)
save_shard_cache(sds, sys.argv[1])
print("SAVED", flush=True)
"""


@pytest.mark.slow
def test_kill_during_shard_ingest_leaves_manifest_uncorrupted(
        tmp_path):
    """A SIGKILL mid-ingest (the ``sharded.ingest`` fault seam) must
    leave the shard-cache manifest exactly as it was: the previously
    committed cache stays loadable byte-for-byte, and restarting the
    construction repairs the cache."""
    d = str(tmp_path / "cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PLAN="")
    ok = subprocess.run([sys.executable, "-c", _KILL_CHILD, d],
                        env=env, cwd=REPO, capture_output=True,
                        text=True, timeout=240)
    assert ok.returncode == 0, ok.stderr[-2000:]
    before = load_shard_cache(d, expect_world_size=3)
    bins_before = np.array(before.assembled_group_bins())
    man_before = open(os.path.join(d, "manifest.json")).read()

    # second construction over the same dir killed at shard 2's ingest
    env["PLAN"] = "sharded.ingest:2:kill"
    killed = subprocess.run([sys.executable, "-c", _KILL_CHILD, d],
                            env=env, cwd=REPO, capture_output=True,
                            text=True, timeout=240)
    assert killed.returncode == -9, (killed.returncode,
                                     killed.stderr[-1000:])
    assert "SAVED" not in killed.stdout
    # the committed manifest is byte-identical and still loads
    assert open(os.path.join(d, "manifest.json")).read() == man_before
    again = load_shard_cache(d, expect_world_size=3)
    assert np.array_equal(again.assembled_group_bins(), bins_before)

    # restarting the shard construction repairs/rewrites cleanly
    env["PLAN"] = ""
    redo = subprocess.run([sys.executable, "-c", _KILL_CHILD, d],
                          env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=240)
    assert redo.returncode == 0, redo.stderr[-2000:]
    final = load_shard_cache(d, expect_world_size=3)
    assert np.array_equal(final.assembled_group_bins(), bins_before)


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------
def test_sharded_config_validation():
    with pytest.raises(ValueError):
        Config.from_params({"sharded_shards": -1})
    with pytest.raises(ValueError):
        Config.from_params({"sharded_sample_per_shard": -2})
    assert Config.from_params({"sharded_shards": 4}).sharded_shards == 4
    with pytest.raises(ValueError):
        ShardedDataset.construct_sharded(np.zeros((4, 2)),
                                         config=Config(), num_shards=0)


def test_sharded_init_score_applied():
    """init_score must ride the sharded route like the single-matrix
    one (review finding: it was silently dropped)."""
    X, y = _corner_data(n=120)
    s = np.linspace(-1.0, 1.0, 120)
    sds = ShardedDataset.construct_sharded(X, label=y, init_score=s,
                                           config=_cfg(), num_shards=2)
    np.testing.assert_array_equal(sds.metadata.init_score,
                                  np.asarray(s, dtype=np.float64))
    core = lgb.Dataset(X, label=y, init_score=s,
                       params=dict(PARAMS,
                                   sharded_shards=2)).construct()
    assert core.metadata.init_score is not None


def test_sharded_shards_exceeding_rows_is_loud():
    """More shards than rows is a hard error, not a silent clamp — a
    clamped world size would commit a cache the next (unchanged) run
    refuses."""
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        ShardedDataset.construct_sharded(
            np.zeros((3, 2)), label=np.zeros(3), config=_cfg(),
            num_shards=5)


def test_sharded_refuses_query_groups():
    from lightgbm_tpu.utils.log import LightGBMError
    X, y = _corner_data(n=60)
    with pytest.raises(LightGBMError):
        ShardedDataset.construct_sharded(
            X, label=y, group=[30, 30], config=_cfg(), num_shards=2)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
