"""HLO regression gate for the packed tree carry (round 7), asserted
through the shared `lightgbm_tpu.analysis` engine since the
static-analysis round.

ROOFLINE round-6 traced the dispatch-chunk degradation (per-tree ≈
25.75 + 0.075·chunk ms on v5e) to the TPU backend's handling of the
fused chunk's EIGHTEEN O(chunk)-sized loop-carried output stacks — one
per TreeArrays field plus the num_leaves series.  The round-7 fix
carries each tree as ONE byte-packed record (tree.TreeRecordLayout),
so the scan's output side holds two buffers: the uint8 record stack
and the num_leaves series.

These tests pin that structure at the compiler seam, for chunk 4 AND
16 (the auto-policy probe sizes), so a refactor that quietly
reintroduces per-field output stacks — or turns the static-offset
record writes back into scattered updates — fails the suite instead of
silently re-opening the chunk slope.  The jaxpr walking and the
bound itself live in ``lightgbm_tpu/analysis`` (rules HLO003/HLO004 +
``walker``): CI's `python -m lightgbm_tpu.analysis` and this file
assert the SAME guarantee through the SAME code.
"""
import re

import jax
import pytest

from lightgbm_tpu.analysis import walker
from lightgbm_tpu.analysis.hlo_rules import (MAX_CARRY_OUTPUT_BUFFERS,
                                             check_carry_bound,
                                             check_dus_not_scatter,
                                             check_no_donation)
from lightgbm_tpu.analysis.programs import build_probe_gbdt, chunk_args

# the legacy per-field carry this refactor retired: 17 TreeArrays
# fields + the num_leaves series
LEGACY_CARRY_OUTPUT_BUFFERS = 18


def _scan_output_stacks(g, chunk):
    """Number of O(chunk) output buffers (ys) the fused chunk's
    boosting scan stacks — read off the jaxpr's scan primitive through
    the shared walker, the exact quantity the backend turns into
    loop-carried output stores."""
    fn = g._build_fused_chunk(chunk)
    jaxpr = jax.make_jaxpr(fn)(*chunk_args(g, chunk)).jaxpr
    assert walker.find_scans(jaxpr), \
        "fused chunk no longer lowers through lax.scan"
    # the boosting scan is the one of length == chunk (inner kernels
    # may scan too, but over other extents)
    boost = walker.find_scans(jaxpr, length=chunk)
    assert boost, f"no scan of length {chunk} in the fused chunk"
    return walker.scan_output_stacks(boost[0])


@pytest.mark.parametrize("chunk", [4, 16])
def test_packed_carry_bounds_output_buffers(analysis_programs, chunk):
    """Rule HLO003 on the registered fused-chunk programs: the carry
    tuple holds at most MAX_CARRY_OUTPUT_BUFFERS O(chunk) output
    stacks (the packed path uses 2: records + num_leaves)."""
    assert analysis_programs.gbdt._packed_carry, \
        "packed_tree_carry must default on"
    prog = analysis_programs.fused_chunk(chunk)
    findings = check_carry_bound(prog)
    assert not findings, "\n".join(f.message for f in findings)


def test_legacy_carry_counter_discriminates(analysis_programs):
    """The same counter must report the 18-buffer legacy carry — if it
    stopped discriminating, the HLO003 bound would be vacuous."""
    g = build_probe_gbdt(packed_tree_carry="off")
    assert not g._packed_carry
    assert _scan_output_stacks(g, 4) == LEGACY_CARRY_OUTPUT_BUFFERS
    # sanity: the packed default stays within the rule bound (probe
    # model reused from the session fixture — no extra training run)
    assert _scan_output_stacks(analysis_programs.gbdt, 4) \
        <= MAX_CARRY_OUTPUT_BUFFERS


def test_record_writes_lower_to_dynamic_update_slice(analysis_programs):
    """Rule HLO004: every tree-record field write lowers to a
    static-offset dynamic-update-slice (the in-place form), never a
    uint8 scatter, and the compiled module keeps DUS instructions
    attributed to tree.py (XLA's simplifier did not rewrite them into
    copies)."""
    prog = analysis_programs.fused_chunk(4)
    findings = check_dus_not_scatter(prog)
    assert not findings, "\n".join(f.message for f in findings)
    # the positive side the rule asserts must not be vacuous here:
    # the program really does carry one DUS per record field
    assert walker.count_op(prog.stablehlo,
                           "stablehlo.dynamic_update_slice") \
        >= prog.meta["record_spec_len"]


def test_donation_stays_off_fused_programs(analysis_programs):
    """Rule HLO006 on both probe chunks + the per-iteration step: the
    r7 heap-corruption bisect pinned donation OFF these multi-shape
    programs."""
    for prog in (analysis_programs.fused_chunk(4),
                 analysis_programs.fused_chunk(16),
                 analysis_programs.fused_step()):
        findings = check_no_donation(prog)
        assert not findings, "\n".join(f.message for f in findings)
        assert prog.donated_args, \
            f"{prog.name}: no args_info — the donation check went blind"


def test_compiled_while_carries_packed_record_stack(analysis_programs):
    """The compiled chunk's outer while-loop tuple must hold the uint8
    record stack (chunk, K, record_size) — the single packed output
    buffer the dispatch scan carries."""
    prog = analysis_programs.fused_chunk(4)
    rec = prog.meta["record_size"]
    pat = re.compile(r"while\(.*u8\[%d,1,%d\]" % (4, rec))
    assert any(pat.search(ln)
               for ln in prog.compiled_text.splitlines()), (
        f"no while loop carries the packed u8[4,1,{rec}] record "
        "stack in the compiled chunk")
