"""HLO regression gate for the packed tree carry (round 7).

ROOFLINE round-6 traced the dispatch-chunk degradation (per-tree ≈
25.75 + 0.075·chunk ms on v5e) to the TPU backend's handling of the
fused chunk's EIGHTEEN O(chunk)-sized loop-carried output stacks — one
per TreeArrays field plus the num_leaves series.  The round-7 fix
carries each tree as ONE byte-packed record (tree.TreeRecordLayout),
so the scan's output side holds two buffers: the uint8 record stack
and the num_leaves series.

These tests pin that structure at the compiler seam, for chunk 4 AND
16 (the auto-policy probe sizes), so a refactor that quietly
reintroduces per-field output stacks — or turns the static-offset
record writes back into scattered updates — fails the suite instead of
silently re-opening the chunk slope.
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.tree import TREE_RECORD_SPEC

# the acceptance bound: carry tuple holds at most this many O(chunk)
# output stacks (the packed path uses 2: records + num_leaves)
MAX_CARRY_OUTPUT_BUFFERS = 4
# the legacy per-field carry this refactor retired: 17 TreeArrays
# fields + the num_leaves series
LEGACY_CARRY_OUTPUT_BUFFERS = 18


def _build_gbdt(**params):
    rng = np.random.RandomState(7)
    X = rng.randn(512, 6)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    cfg = Config.from_params({"objective": "binary", "num_leaves": 7,
                              "verbose": -1, "min_data_in_leaf": 5,
                              **params})
    core = lgb.Dataset(X, label=y).construct(cfg)
    return GBDT(cfg, core)


def _chunk_args(g, chunk):
    keys = jnp.zeros((chunk, 2), jnp.uint32)
    fmasks = jnp.ones((chunk, g.num_class, g.grower.num_features), bool)
    fresh = jnp.zeros(chunk, bool)
    return (g.scores, tuple(), g._full_counts > 0, keys, fmasks, fresh)


def _scan_output_stacks(g, chunk):
    """Number of O(chunk) output buffers (ys) the fused chunk's
    boosting scan stacks — read off the jaxpr's scan primitive, the
    exact quantity the backend turns into loop-carried output stores."""
    fn = g._build_fused_chunk(chunk)
    jaxpr = jax.make_jaxpr(fn)(*_chunk_args(g, chunk))

    def find(jx, out):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                out.append(eqn)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr"):
                    find(v.jaxpr, out)
        return out

    scans = find(jaxpr.jaxpr, [])
    assert scans, "fused chunk no longer lowers through lax.scan"
    # the boosting scan is the one of length == chunk (inner kernels
    # may scan too, but over other extents)
    boost = [s for s in scans if s.params.get("length") == chunk]
    assert boost, f"no scan of length {chunk} in the fused chunk"
    top = boost[0]
    return len(top.outvars) - top.params["num_carry"]


@pytest.mark.parametrize("chunk", [4, 16])
def test_packed_carry_bounds_output_buffers(chunk):
    g = _build_gbdt()
    assert g._packed_carry, "packed_tree_carry must default on"
    ys = _scan_output_stacks(g, chunk)
    assert ys <= MAX_CARRY_OUTPUT_BUFFERS, (
        f"fused chunk stacks {ys} loop-carried output buffers at chunk "
        f"{chunk}; the packed-carry bound is {MAX_CARRY_OUTPUT_BUFFERS}"
        " (round-6 diagnosis: per-field stacks are what made per-tree "
        "cost grow with chunk length)")


def test_legacy_carry_counter_discriminates():
    """The same counter must report the 18-buffer legacy carry — if it
    stopped discriminating, the bound above would be vacuous."""
    g = _build_gbdt(packed_tree_carry="off")
    assert not g._packed_carry
    assert _scan_output_stacks(g, 4) == LEGACY_CARRY_OUTPUT_BUFFERS


@pytest.fixture(scope="module")
def lowered_chunk4():
    """One shared lower()+compile() of the chunk-4 program — every
    compiled-HLO assertion below reads the same artifact."""
    g = _build_gbdt()
    fn = g._build_fused_chunk(4)
    low = fn.lower(*_chunk_args(g, 4))
    return g, low, low.compile().as_text()


def test_record_writes_lower_to_dynamic_update_slice(lowered_chunk4):
    """Every tree-record field write must lower to a static-offset
    dynamic-update-slice (the in-place form), never a windowed scatter:
    one DUS per TREE_RECORD_SPEC field in the StableHLO, and the
    compiled module keeps DUS instructions attributed to tree.py
    (XLA's simplifier did not rewrite them into copies)."""
    g, low, hlo = lowered_chunk4

    txt = low.as_text()
    n_dus = txt.count("stablehlo.dynamic_update_slice")
    # 17 field writes + the scan's 2 output-stack updates
    assert n_dus >= len(TREE_RECORD_SPEC), (
        f"only {n_dus} dynamic_update_slice ops in the lowered chunk — "
        f"expected one per record field ({len(TREE_RECORD_SPEC)}); "
        "record emission regressed to scatter")
    # no scatter may write a uint8 operand (the record buffer is the
    # only u8 tensor in the program)
    for m in re.finditer(r'"stablehlo\.scatter"\(([^)]*)\)', txt):
        assert "ui8" not in m.group(1), (
            "a tree-record write lowered to stablehlo.scatter: "
            f"{m.group(0)[:160]}")

    dus_tree = [ln for ln in hlo.splitlines()
                if "dynamic-update-slice" in ln and "tree.py" in ln]
    assert dus_tree, ("compiled HLO carries no dynamic-update-slice "
                      "attributed to tree.py — record writes were "
                      "rewritten out of in-place form")


def test_compiled_while_carries_packed_record_stack(lowered_chunk4):
    """The compiled chunk's outer while-loop tuple must hold the uint8
    record stack (chunk, K, record_size) — the single packed output
    buffer the dispatch scan carries."""
    g, _low, hlo = lowered_chunk4
    chunk = 4
    rec = g.grower.record_layout.record_size
    pat = re.compile(r"while\(.*u8\[%d,1,%d\]" % (chunk, rec))
    assert any(pat.search(ln) for ln in hlo.splitlines()), (
        f"no while loop carries the packed u8[{chunk},1,{rec}] record "
        "stack in the compiled chunk")
