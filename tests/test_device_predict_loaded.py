"""Raw-feature stacked device predict parity: every model kind the
in-session binned path cannot serve (file-loaded, multiclass, DART,
init_model-merged, categorical, refit) must produce scores matching the
host per-tree walk (reference c_api.cpp:177-211 batch predict covers
every model; so must the device path).  The walk itself is pure XLA, so
``device=True`` exercises the identical code on the CPU backend."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _task(n=600, f=8, seed=0, with_nan=True, with_cat=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if with_cat:
        X[:, -1] = rng.randint(0, 12, n)
    if with_nan:
        X[rng.rand(n, f) < 0.05] = np.nan
        if with_cat:
            X[:, -1] = np.where(np.isnan(X[:, -1]),
                                rng.randint(0, 12, n), X[:, -1])
    y = (np.nan_to_num(X[:, 0]) - 0.5 * np.nan_to_num(X[:, 1])
         + 0.1 * rng.randn(n) > 0).astype(float)
    return X, y


def _assert_device_matches_host(bst, X, **kw):
    host = bst.predict(X, device=False, **kw)
    dev = bst.predict(X, device=True, **kw)
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-7)


def test_loaded_model_device_predict(tmp_path):
    X, y = _task()
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 31, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), 20, verbose_eval=False)
    fn = str(tmp_path / "m.txt")
    bst.save_model(fn)
    loaded = lgb.Booster(model_file=fn)
    _assert_device_matches_host(loaded, X)
    _assert_device_matches_host(loaded, X, raw_score=True)
    # num_iteration slicing resolves identically on both paths
    _assert_device_matches_host(loaded, X, num_iteration=7)


def test_multiclass_device_predict():
    X, y2 = _task(with_nan=False)
    y = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "verbose": -1, "num_leaves": 15},
                    lgb.Dataset(X, label=y), 8, verbose_eval=False)
    host = bst.predict(X, device=False)
    dev = bst.predict(X, device=True)
    assert dev.shape == (X.shape[0], 3)
    np.testing.assert_allclose(dev, host, rtol=2e-5, atol=2e-6)


def test_dart_device_predict():
    X, y = _task(with_nan=False)
    bst = lgb.train({"objective": "binary", "boosting": "dart",
                     "verbose": -1, "num_leaves": 15, "drop_rate": 0.5,
                     "seed": 3}, lgb.Dataset(X, label=y), 10,
                    verbose_eval=False)
    _assert_device_matches_host(bst, X)


def test_categorical_device_predict():
    X, y = _task(with_cat=True)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5,
                     "max_cat_to_onehot": 2},
                    lgb.Dataset(X, label=y,
                                categorical_feature=[X.shape[1] - 1]),
                    15, verbose_eval=False)
    _assert_device_matches_host(bst, X)


def test_init_model_merged_device_predict():
    X, y = _task(with_nan=False)
    p = {"objective": "binary", "verbose": -1, "num_leaves": 15}
    base = lgb.train(p, lgb.Dataset(X, label=y), 5, verbose_eval=False)
    cont = lgb.train(p, lgb.Dataset(X, label=y, free_raw_data=False), 5,
                     verbose_eval=False, init_model=base)
    _assert_device_matches_host(cont, X)


def test_refit_then_device_predict():
    X, y = _task(with_nan=False)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 15}, lgb.Dataset(X, label=y), 8,
                    verbose_eval=False)
    bst.refit(X, y)
    # refit invalidates the stale caches; the raw-stack path rebuilds
    # from the refitted host trees
    _assert_device_matches_host(bst, X)


def test_midpoint_threshold_exactness():
    """Rows landing exactly on the f32 neighbour of an f64 midpoint
    threshold must route the same on device (two-float compare) as on
    the host float64 walk."""
    rng = np.random.RandomState(7)
    # f32-representable data with adjacent values around every split
    X = rng.randn(2000, 3).astype(np.float32).astype(np.float64)
    y = (X[:, 0] > 0.1).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 63, "min_data_in_leaf": 1,
                     "min_sum_hessian_in_leaf": 1e-3},
                    lgb.Dataset(X, label=y), 10, verbose_eval=False)
    leaf_host = bst.predict(X, pred_leaf=True)
    # the device path must place every row in the same leaf: compare
    # raw scores bitwise at f32 resolution
    host = bst.predict(X, device=False, raw_score=True)
    dev = bst.predict(X, device=True, raw_score=True)
    np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)
    assert leaf_host.shape[1] == 10
