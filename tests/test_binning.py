"""BinMapper semantics tests (reference bin.cpp:73-390 behavior)."""
import numpy as np
import pytest

from lightgbm_tpu.binning import (BIN_CATEGORICAL, BIN_NUMERICAL,
                                  MISSING_NAN, MISSING_NONE, MISSING_ZERO,
                                  BinMapper, greedy_find_bin)


def _fit(values, total=None, max_bin=255, min_data_in_bin=3,
         bin_type=BIN_NUMERICAL, use_missing=True, zero_as_missing=False):
    values = np.asarray(values, dtype=np.float64)
    total = total if total is not None else len(values)
    m = BinMapper()
    m.find_bin(values, total, max_bin, min_data_in_bin, 2, bin_type,
               use_missing, zero_as_missing)
    return m


def test_simple_numerical():
    vals = np.repeat(np.arange(1, 11, dtype=float), 10)
    m = _fit(vals)
    assert not m.is_trivial
    assert m.num_bin == 11  # 10 values + zero bin
    bins = m.value_to_bin(np.array([1.0, 5.0, 10.0]))
    assert bins[0] < bins[1] < bins[2]


def test_zero_gets_own_bin():
    vals = np.array([-2.0] * 30 + [3.0] * 30)
    m = _fit(vals, total=90)  # 30 implicit zeros
    zb = m.value_to_bin(np.array([0.0]))[0]
    nb = m.value_to_bin(np.array([-2.0]))[0]
    pb = m.value_to_bin(np.array([3.0]))[0]
    assert nb < zb < pb
    assert m.default_bin == zb


def test_missing_nan_bin():
    vals = np.array([1.0, 2.0, 3.0] * 20 + [np.nan] * 10)
    m = _fit(vals)
    assert m.missing_type == MISSING_NAN
    nanb = m.value_to_bin(np.array([np.nan]))[0]
    assert nanb == m.num_bin - 1


def test_no_missing():
    vals = np.array([1.0, 2.0, 3.0] * 20)
    m = _fit(vals)
    assert m.missing_type == MISSING_NONE
    # NaN at predict time maps like 0.0
    assert m.value_to_bin(np.array([np.nan]))[0] == \
        m.value_to_bin(np.array([0.0]))[0]


def test_zero_as_missing():
    vals = np.array([1.0, 2.0, 3.0, -1.0] * 20)
    m = _fit(vals, total=100, zero_as_missing=True)
    assert m.missing_type == MISSING_ZERO


def test_trivial_constant():
    m = _fit(np.array([5.0] * 50))
    assert m.is_trivial


def test_categorical_mapping():
    vals = np.array([1.0] * 50 + [2.0] * 30 + [7.0] * 20)
    m = _fit(vals, bin_type=BIN_CATEGORICAL)
    assert m.bin_type == BIN_CATEGORICAL
    b1 = m.value_to_bin(np.array([1.0]))[0]
    b2 = m.value_to_bin(np.array([2.0]))[0]
    b7 = m.value_to_bin(np.array([7.0]))[0]
    # most-frequent-first ordering
    assert b1 < b2 < b7
    # unseen category falls into the last bin
    assert m.value_to_bin(np.array([99.0]))[0] == m.num_bin - 1


def test_categorical_negative_is_nan():
    vals = np.array([1.0] * 50 + [-3.0] * 10)
    m = _fit(vals, bin_type=BIN_CATEGORICAL)
    assert m.value_to_bin(np.array([-3.0]))[0] == m.num_bin - 1


def test_greedy_find_bin_respects_max_bin():
    dv = np.arange(1000, dtype=np.float64)
    cnt = np.ones(1000, dtype=np.int64)
    bounds = greedy_find_bin(dv, cnt, 16, 1000, 0)
    assert len(bounds) <= 16
    assert bounds[-1] == np.inf


def test_value_to_bin_roundtrip_monotone():
    rng = np.random.RandomState(0)
    vals = rng.randn(5000)
    m = _fit(vals, max_bin=63)
    x = np.sort(rng.randn(1000))
    bins = m.value_to_bin(x)
    assert np.all(np.diff(bins) >= 0)  # monotone mapping
    assert bins.max() < m.num_bin


def test_native_binning_byte_identical_to_python():
    """The native compare-count binner + blocked column scatter
    (native/src/bin_dense.cpp) must produce the EXACT packed matrix
    the numpy searchsorted path does — NaNs, zero bins, and the
    wide-matrix layout included."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config

    rng = np.random.RandomState(7)
    n, f = 6000, 40                      # > the 4096 native threshold
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.05] = np.nan
    X[rng.rand(n, f) < 0.1] = 0.0
    y = rng.rand(n)
    a = lgb.Dataset(X, label=y).construct(
        Config.from_params({"max_bin": 63, "verbose": -1}))
    b = lgb.Dataset(X, label=y).construct(
        Config.from_params({"max_bin": 63, "verbose": -1,
                            "native_binning": False}))
    assert (np.asarray(a.group_bins) == np.asarray(b.group_bins)).all()
