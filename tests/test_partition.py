"""Row-routing correctness for the XLA split router.

Regression coverage for the >256-feature-group case: the leaf table
packs feat_group hi/lo into two bf16 byte columns (a single bf16 column
is exact only up to 256 — group ids >= 257 would decode wrong and rows
would read a different group's bins).
"""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.partition import (MISSING_NAN, MISSING_NONE,
                                        MISSING_ZERO, apply_splits)


def _route_numpy(bins, leaf_id, split_mask, feat_group, fb_lo, fb_hi,
                 fb_shift, fb_oor, is_cat, threshold, default_left,
                 missing_type, default_bin, num_bin, cat_mask, right_slot):
    """Scalar reference of the routing semantics."""
    out = leaf_id.copy()
    for r in range(len(leaf_id)):
        leaf = leaf_id[r]
        if leaf < 0 or not split_mask[leaf]:
            continue
        g = feat_group[leaf]
        gb = int(bins[r, g])
        if fb_lo[leaf] <= gb < fb_hi[leaf]:
            fbin = gb - fb_shift[leaf]
        else:
            fbin = fb_oor[leaf]
        if is_cat[leaf]:
            left = bool(cat_mask[leaf, fbin])
        elif missing_type[leaf] == MISSING_NAN and fbin == num_bin[leaf] - 1:
            left = bool(default_left[leaf])
        elif missing_type[leaf] == MISSING_ZERO and fbin == default_bin[leaf]:
            left = bool(default_left[leaf])
        else:
            left = fbin <= threshold[leaf]
        out[r] = leaf if left else right_slot[leaf]
    return out


def _make_case(rng, n=512, num_groups=300, L=8, B=16):
    """Synthetic split state: leaves 0..3 split, on groups straddling
    the 256 boundary; a mix of missing types and one categorical."""
    bins = rng.randint(0, B, (n, num_groups)).astype(np.uint8)
    leaf_id = rng.randint(-1, 6, n).astype(np.int32)
    split_mask = np.zeros(L, bool)
    split_mask[:4] = True
    feat_group = np.array([3, 257, 290, 299, 0, 0, 0, 0], np.int32)
    fb_lo = np.zeros(L, np.int32)
    fb_hi = np.full(L, B, np.int32)
    fb_shift = np.zeros(L, np.int32)
    fb_oor = np.full(L, B - 1, np.int32)
    is_cat = np.array([0, 0, 0, 1, 0, 0, 0, 0], bool)
    threshold = np.array([7, 3, 11, 5, 0, 0, 0, 0], np.int32)
    default_left = np.array([1, 0, 1, 0, 0, 0, 0, 0], bool)
    missing_type = np.array([MISSING_NONE, MISSING_ZERO, MISSING_NAN, 0,
                             0, 0, 0, 0], np.int32)
    default_bin = np.array([0, 2, 0, 0, 0, 0, 0, 0], np.int32)
    num_bin = np.full(L, B, np.int32)
    cat_mask = rng.rand(L, B) > 0.5
    right_slot = np.array([8, 9, 10, 11, 0, 0, 0, 0], np.int32)
    return (bins, leaf_id, split_mask, feat_group, fb_lo, fb_hi, fb_shift,
            fb_oor, is_cat, threshold, default_left, missing_type,
            default_bin, num_bin, cat_mask, right_slot)


def test_apply_splits_matches_reference_over_256_groups(rng):
    args = _make_case(rng)
    want = _route_numpy(*args)
    got = np.asarray(apply_splits(*[jnp.asarray(a) for a in args]))
    np.testing.assert_array_equal(got, want)
