"""Row-routing correctness for the XLA split router.

Regression coverage for the >256-feature-group case: the leaf table
packs feat_group hi/lo into two bf16 byte columns (a single bf16 column
is exact only up to 256 — group ids >= 257 would decode wrong and rows
would read a different group's bins).
"""
import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.ops.partition import (MISSING_NAN, MISSING_NONE,
                                        MISSING_ZERO, apply_splits)


def _route_numpy(bins, leaf_id, split_mask, feat_group, fb_lo, fb_hi,
                 fb_shift, fb_oor, is_cat, threshold, default_left,
                 missing_type, default_bin, num_bin, cat_mask, right_slot):
    """Scalar reference of the routing semantics."""
    out = leaf_id.copy()
    for r in range(len(leaf_id)):
        leaf = leaf_id[r]
        if leaf < 0 or not split_mask[leaf]:
            continue
        g = feat_group[leaf]
        gb = int(bins[r, g])
        if fb_lo[leaf] <= gb < fb_hi[leaf]:
            fbin = gb - fb_shift[leaf]
        else:
            fbin = fb_oor[leaf]
        if is_cat[leaf]:
            left = bool(cat_mask[leaf, fbin])
        elif missing_type[leaf] == MISSING_NAN and fbin == num_bin[leaf] - 1:
            left = bool(default_left[leaf])
        elif missing_type[leaf] == MISSING_ZERO and fbin == default_bin[leaf]:
            left = bool(default_left[leaf])
        else:
            left = fbin <= threshold[leaf]
        out[r] = leaf if left else right_slot[leaf]
    return out


def _make_case(rng, n=512, num_groups=300, L=8, B=16):
    """Synthetic split state: leaves 0..3 split, on groups straddling
    the 256 boundary; a mix of missing types and one categorical."""
    bins = rng.randint(0, B, (n, num_groups)).astype(np.uint8)
    leaf_id = rng.randint(-1, 6, n).astype(np.int32)
    split_mask = np.zeros(L, bool)
    split_mask[:4] = True
    feat_group = np.array([3, 257, 290, 299, 0, 0, 0, 0], np.int32)
    fb_lo = np.zeros(L, np.int32)
    fb_hi = np.full(L, B, np.int32)
    fb_shift = np.zeros(L, np.int32)
    fb_oor = np.full(L, B - 1, np.int32)
    is_cat = np.array([0, 0, 0, 1, 0, 0, 0, 0], bool)
    threshold = np.array([7, 3, 11, 5, 0, 0, 0, 0], np.int32)
    default_left = np.array([1, 0, 1, 0, 0, 0, 0, 0], bool)
    missing_type = np.array([MISSING_NONE, MISSING_ZERO, MISSING_NAN, 0,
                             0, 0, 0, 0], np.int32)
    default_bin = np.array([0, 2, 0, 0, 0, 0, 0, 0], np.int32)
    num_bin = np.full(L, B, np.int32)
    cat_mask = rng.rand(L, B) > 0.5
    right_slot = np.array([8, 9, 10, 11, 0, 0, 0, 0], np.int32)
    return (bins, leaf_id, split_mask, feat_group, fb_lo, fb_hi, fb_shift,
            fb_oor, is_cat, threshold, default_left, missing_type,
            default_bin, num_bin, cat_mask, right_slot)


def test_apply_splits_matches_reference_over_256_groups(rng):
    args = _make_case(rng)
    want = _route_numpy(*args)
    got = np.asarray(apply_splits(*[jnp.asarray(a) for a in args]))
    np.testing.assert_array_equal(got, want)


def test_leaf_partition_roundtrip_property(rng):
    """build_leaf_partition invariants over random leaf layouts: the
    permutation is a bijection onto the real rows, segments are stable
    (source order preserved within a leaf), block-aligned, and every
    block's ownership map matches the rows it actually holds; gathering
    through the permutation reconstructs exactly the per-leaf row sets
    (the round-trip the grower's segment kernel relies on)."""
    from lightgbm_tpu.ops.partition import (apply_partition,
                                            build_leaf_partition,
                                            partition_capacity)

    for n, L, block in ((256, 3, 64), (1024, 17, 128), (512, 255, 256)):
        leaf = rng.randint(-1, L, n).astype(np.int32)
        # exercise empty leaves and a dominant leaf too
        leaf[rng.rand(n) < 0.3] = min(2, L - 1)
        perm, blk_leaf, seg_count = build_leaf_partition(
            jnp.asarray(leaf), num_slots=L, block=block)
        perm_np = np.asarray(perm)
        blk_np = np.asarray(blk_leaf)
        cnt_np = np.asarray(seg_count)
        assert perm_np.shape == (partition_capacity(n, L, block),)
        real = perm_np[perm_np >= 0]
        assert sorted(real.tolist()) == list(range(n))
        lid = np.where(leaf >= 0, leaf, L)
        assert cnt_np.sum() == n
        np.testing.assert_array_equal(cnt_np, np.bincount(lid,
                                                          minlength=L + 1))
        pos_of = {int(r): i for i, r in enumerate(perm_np) if r >= 0}
        for w in range(L + 1):
            rows = np.flatnonzero(lid == w)
            positions = [pos_of[int(r)] for r in rows]
            # contiguity + stability: consecutive positions, source order
            assert positions == sorted(positions)
            if len(positions):
                assert positions[-1] - positions[0] == len(positions) - 1
                assert positions[0] % block == 0  # aligned segment start
        for bi, w in enumerate(blk_np):
            rows = perm_np[bi * block:(bi + 1) * block]
            rows = rows[rows >= 0]
            if w >= 0:
                assert np.all(lid[rows] == w)
            else:  # dead block: gap tail, invalid bucket, or capacity
                assert len(rows) == 0 or np.all(lid[rows] == L)
        # gather round-trip: partitioned leaf ids match block ownership
        leaf_p = np.asarray(apply_partition(
            jnp.asarray(np.where(leaf >= 0, leaf, -7)), perm))
        for bi, w in enumerate(blk_np):
            if w >= 0:
                blk = leaf_p[bi * block:(bi + 1) * block]
                assert set(blk[perm_np[bi * block:(bi + 1) * block] >= 0]
                           .tolist()) <= {int(w)}


def test_apply_partition_masks_gap_rows(rng):
    """Gap entries (-1) must read as ZERO, not wrap to the last row —
    jnp.take's python-style negative wrapping under mode="fill" aliased
    the final source row into every alignment gap (caught by the
    segment-kernel parity test during development; pinned here)."""
    from lightgbm_tpu.ops.partition import apply_partition

    arr = jnp.asarray(rng.randint(1, 9, (3, 16)).astype(np.int32))
    perm = jnp.asarray(np.array([0, 15, -1, 7, -1], np.int32))
    out = np.asarray(apply_partition(arr, perm, axis=1))
    arr_np = np.asarray(arr)
    np.testing.assert_array_equal(out[:, 0], arr_np[:, 0])
    np.testing.assert_array_equal(out[:, 1], arr_np[:, 15])
    np.testing.assert_array_equal(out[:, 3], arr_np[:, 7])
    assert (out[:, 2] == 0).all() and (out[:, 4] == 0).all()
