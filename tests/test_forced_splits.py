"""Forced splits (reference serial_tree_learner.cpp:543-698 ForceSplits,
examples in docs/Parameters.rst forcedsplits_filename)."""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_data(n=600, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    # feature 1 is by far the best split; feature 0 is weak
    y = (X[:, 1] > 0).astype(float) * 2.0 + 0.1 * (X[:, 0] > 0.5)
    return X, y


def test_forced_root_split(tmp_path):
    X, y = _make_data()
    spec = {"feature": 0, "threshold": 0.5,
            "left": {"feature": 2, "threshold": -0.25}}
    fn = str(tmp_path / "forced.json")
    with open(fn, "w") as f:
        json.dump(spec, f)

    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "min_data_in_leaf": 5, "verbose": -1,
                     "forcedsplits_filename": fn},
                    lgb.Dataset(X, label=y), 3)
    dump = bst.dump_model()
    root = dump["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 0
    assert abs(root["threshold"] - 0.5) < 0.3
    # the root's LEFT child must be forced on feature 2
    left = root["left_child"]
    assert left["split_feature"] == 2
    assert abs(left["threshold"] - (-0.25)) < 0.3
    # without forcing, the root split would be feature 1
    bst2 = lgb.train({"objective": "regression", "num_leaves": 8,
                      "min_data_in_leaf": 5, "verbose": -1},
                     lgb.Dataset(X, label=y), 3)
    root2 = bst2.dump_model()["tree_info"][0]["tree_structure"]
    assert root2["split_feature"] == 1
    # forced model must still fit the dominant signal eventually
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < np.var(y)


def test_forced_split_unused_feature_ignored(tmp_path):
    X, y = _make_data()
    X[:, 2] = 7.0      # constant -> dropped from training
    fn = str(tmp_path / "forced.json")
    with open(fn, "w") as f:
        json.dump({"feature": 2, "threshold": 1.0}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbose": -1, "forcedsplits_filename": fn},
                    lgb.Dataset(X, label=y), 2)
    root = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 1   # normal growth


def test_forced_split_bad_gain_falls_back(tmp_path):
    X, y = _make_data()
    fn = str(tmp_path / "forced.json")
    # threshold far outside the data range -> empty side, gain invalid
    with open(fn, "w") as f:
        json.dump({"feature": 0, "threshold": 1e9}, f)
    bst = lgb.train({"objective": "regression", "num_leaves": 8,
                     "verbose": -1, "forcedsplits_filename": fn},
                    lgb.Dataset(X, label=y), 2)
    root = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 1   # fell back to the best split
