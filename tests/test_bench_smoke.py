"""bench.py plumbing regression gate.

The r5 perf artifact was an rc=124 timeout — a bench-only code path
(unbudgeted local-reference anchors) that nothing in the suite
exercised.  This runs the tiny-N smoke driver (scripts/bench_smoke.sh:
BENCH_ITERS=2, BENCH_LOCAL_REF=0) as a subprocess and pins the bench's
stdout contract: exactly one parseable JSON line carrying every field
the perf driver reads.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_json_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_CHUNK="1")
    run = subprocess.run(
        ["sh", os.path.join(REPO, "scripts", "bench_smoke.sh")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=640)
    assert run.returncode == 0, (run.stdout or "")[-2000:] + \
        (run.stderr or "")[-2000:]
    lines = [ln for ln in run.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines!r}"
    out = json.loads(lines[0])
    for field in ("metric", "value", "unit", "vs_baseline", "auc",
                  "auc_delta", "scales", "budget"):
        assert field in out, f"missing {field}"
    assert out["budget"]["elapsed_s"] <= out["budget"]["budget_s"]
    tasks = {s.get("task", "binary") for s in out["scales"]}
    assert "lambdarank" in tasks, "LTR scale must run in the smoke"
    ltr = next(s for s in out["scales"] if s.get("task") == "lambdarank")
    # the same-data NDCG gate must EXECUTE or say why it didn't
    assert "ndcg_gate" in ltr
    # serving roofline block (round 8): bulk throughput, micro-batch
    # p50, compile telemetry and the parity gate result
    assert "predict" in out, "predict scale must run in the smoke"
    p = out["predict"]
    for field in ("bulk_rows_per_s", "p50_ms", "small_batch",
                  "compile_count", "buckets_used", "parity"):
        assert field in p, f"predict block missing {field}"
    assert p["parity"] == "pass"
    # compile-count lint: ONE compilation per shape bucket — every
    # batch size inside a bucket reuses the bucket's program
    assert p["compile_count"] == len(p["buckets_used"]), (
        f"{p['compile_count']} compiles for buckets "
        f"{p['buckets_used']} — bucketed predict must compile once "
        "per bucket")
    assert p["dispatches"] > p["compile_count"], \
        "smoke issued no cache-hit dispatches"
    # construction roofline block (round 11): cold vs serial rows/s,
    # thread scaling, cache-v2 reload — parity gated inside the bench
    assert "construct" in out, "construct scale must run in the smoke"
    c = out["construct"]
    for field in ("rows", "features", "cold_construct_s",
                  "cold_rows_per_s", "serial_construct_s",
                  "serial_rows_per_s", "speedup_vs_serial",
                  "threads_auto", "thread_scaling", "cache_save_s",
                  "cache_reload_s", "reload_x_cold", "parity"):
        assert field in c, f"construct block missing {field}"
    assert c["parity"] == "pass"
    assert set(c["thread_scaling"]) == {"1", "auto", "x"}
    # the anchor must be present or carry an explicit skip reason
    assert "local_ref" in c or "local_ref_skipped" in c
    # sharded-construct probe (round 16): 2 simulated participants,
    # merged-mapper + bin parity vs the single-matrix route, merge
    # wall, RSS per route, shard-cache v2 manifest round trip with
    # the wrong-world-size refusal exercised
    assert "shard_construct" in out, \
        "shard_construct probe must run in the smoke"
    sc = out["shard_construct"]
    for field in ("rows", "shards", "shard_construct_s",
                  "shard_rows_per_s", "per_shard_rows_per_s",
                  "single_construct_s", "merge_wall_ms",
                  "rss_single_mb", "rss_sharded_mb", "cache_reload_s",
                  "parity", "manifest_reject"):
        assert field in sc, f"shard_construct block missing {field}"
    assert sc["shards"] == 2, "smoke runs 2 simulated participants"
    assert sc["parity"] == "pass"
    assert sc["manifest_reject"] == "pass"
    # compact-bins probe (round 18): nibble-packed (bin_packing=4bit)
    # pipeline vs 8-bit on the same max_bin=15 draw — >=2x packing
    # ratio (host AND gauge-measured device matrix), construct rows/s
    # per mode, the histogram bytes-read model, byte-identical trees
    assert "compact_bins" in out, \
        "compact_bins probe must run in the smoke"
    cb = out["compact_bins"]
    for field in ("rows", "max_bin", "construct_rows_per_s_8bit",
                  "construct_rows_per_s_4bit",
                  "construct_ratio_4bit_vs_8bit",
                  "host_matrix_bytes_8bit", "host_matrix_bytes_4bit",
                  "bin_matrix_bytes_8bit", "bin_matrix_bytes_4bit",
                  "packing_ratio", "device_packing_ratio",
                  "hist_bytes_per_row_8bit", "hist_bytes_per_row_4bit",
                  "hist_stream_ratio", "parity",
                  # round-21 crumb tier + compressed exchange fields
                  "construct_rows_per_s_2bit_mb4",
                  "host_matrix_bytes_2bit", "bin_matrix_bytes_2bit",
                  "crumb_packing_ratio", "crumb_predicted_ratio",
                  "crumb_device_ratio", "hist_bytes_per_row_2bit",
                  "crumb_stream_ratio", "hist_exchange_bytes_f32",
                  "hist_exchange_bytes_q16", "hist_exchange_bytes_q8",
                  "hist_exchange_ratio_q16", "hist_exchange_ratio_q8"):
        assert field in cb, f"compact_bins block missing {field}"
    assert cb["max_bin"] == 15
    assert cb["packing_ratio"] >= 2.0, \
        "4-bit matrix must halve the 8-bit bytes at max_bin=15"
    # acceptance: device matrix <= 0.55x the 8-bit bytes, gauge-measured
    # (a zero gauge would make the ratio assert pass vacuously)
    assert cb["bin_matrix_bytes_8bit"] > 0, \
        "bin_matrix_bytes gauge must be measured, not defaulted"
    assert cb["bin_matrix_bytes_4bit"] <= \
        0.55 * cb["bin_matrix_bytes_8bit"]
    # crumb tier: the measured host ratio meets the layout-predicted
    # G / ceil(G/4) read-stream reduction on the max_bin=4 sub-draw
    assert cb["crumb_packing_ratio"] >= cb["crumb_predicted_ratio"]
    assert cb["bin_matrix_bytes_2bit"] > 0
    # compressed exchange: the wire payload genuinely shrinks 2x / 4x
    assert cb["hist_exchange_bytes_f32"] > 0
    assert cb["hist_exchange_ratio_q16"] >= 2.0
    assert cb["hist_exchange_ratio_q8"] >= 4.0
    assert cb["parity"] == "pass"
    # distributed-exchange probe (this round): the r21 hist_exchange
    # codec over the REAL 2-process TCP transport — per-mode wire
    # bytes from the collective_tcp_* per-primitive counters, q16/q8
    # payload-reduction gates, every mode bit-exact vs the host codec
    # inside the workers
    assert "distributed_exchange" in out, \
        "distributed_exchange probe must run in the smoke"
    dx = out["distributed_exchange"]
    for field in ("world", "hist_shape", "modes", "wire_ratio_q16",
                  "wire_ratio_q8", "total_wire_ratio_q16", "parity",
                  "wire_gate", "crc", "crc_overhead_frac", "crc_gate"):
        assert field in dx, f"distributed_exchange block missing {field}"
    assert dx["world"] == 2
    assert dx["parity"] == "pass" and dx["wire_gate"] == "pass"
    # frame-integrity budget (ISSUE 20): the tiered payload digest
    # must cost < 2% of the q16 wire-path wall
    assert dx["crc_gate"] == "pass"
    assert 0.0 <= dx["crc_overhead_frac"] < 0.02
    assert dx["crc"]["q16_wire_bytes"] > 0
    assert dx["wire_ratio_q16"] >= 2.0, \
        "q16 must halve the f32 wire payload over real TCP"
    assert dx["wire_ratio_q8"] >= 4.0
    for mode in ("f32", "q16", "q8"):
        assert dx["modes"][mode]["payload_wire_bytes"] > 0, \
            f"{mode} wire bytes must be measured, not defaulted"
    # the scale sync must actually cross the wire in the q modes
    assert dx["modes"]["q16"]["scale_wire_bytes"] > 0
    assert dx["modes"]["f32"]["scale_wire_bytes"] == 0
    # reliability probe (round 12): checkpoint save overhead measured
    # and the smoke fault-plan recovery (SIGKILL mid-train -> resume)
    # byte-identical — scripts/reliability_probe.py, run in-line by
    # bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/reliability.json") as f:
        r = json.load(f)
    for field in ("save_ms_per_snapshot", "checkpoint_saves",
                  "cold_wall_s", "resume_wall_s",
                  "resume_vs_cold_delta_s", "kill_returncode",
                  "byte_identical", "kill_recovery"):
        assert field in r, f"reliability probe missing {field}"
    assert r["kill_recovery"] == "pass"
    assert r["kill_returncode"] == -9, "harness must really SIGKILL"
    assert r["byte_identical"] is True
    assert r["checkpoint_saves"] >= 2
    assert r["save_ms_per_snapshot"] > 0
    # chaos probe (round 19): seeded randomized multi-fault plans
    # across train/serve/continuous, gated by the invariant registry
    # — scripts/chaos_probe.py, run in-line by bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/chaos.json") as f:
        ch = json.load(f)
    for field in ("plans_run", "plans_green", "plans", "invariants",
                  "faults_injected", "status"):
        assert field in ch, f"chaos probe missing {field}"
    assert ch["status"] == "pass"
    assert ch["plans_green"] == ch["plans_run"]
    if ch["budget_exceeded"]:
        # CHAOS_BUDGET_S tripped on a slow machine: the sweep stops
        # with a note INSTEAD of blowing the smoke wall — whatever ran
        # must still be green, but the floor below is waived
        assert ch["plans_run"] >= 1
    else:
        # the acceptance floor: >= 12 seeded plans across all three
        # workloads, every one green, every plan carrying its seed +
        # expanded spec for replay
        assert ch["plans_run"] >= 20, \
            f"chaos sweep ran only {ch['plans_run']} plans"
        # in-process workloads (serve/continuous) count into the
        # probe's own faults_injected; train faults fire in
        # subprocesses.  A zero here would mean the draws never hit a
        # live seam — vacuous plans
        assert ch["faults_injected"] >= 4
        workloads = {p["workload"] for p in ch["plans"]}
        assert workloads == {"train", "serve", "continuous",
                             "transport"}
    for p in ch["plans"]:
        assert p["green"] and not p["violations"], p
        assert isinstance(p["seed"], int) and p["plan"], \
            "a chaos plan must be replayable from its seed"
    assert set(ch["invariants"]) >= {
        "resume_byte_identical", "no_partial_artifacts",
        "ledger_converges", "serving_parity", "loud_failure",
        "transport_no_silent_misdata", "partition_heals",
        "coordinator_failover"}
    # distributed-observability probe (round 13): the Prometheus
    # textfile was written and scrape-parsed (bucket monotonicity is
    # asserted inside bench_smoke.sh), and the flight-recorder smoke
    # left a dump naming the injected seam
    import glob
    with open("/tmp/lgbtpu_smoke/metrics.prom") as f:
        prom = f.read()
    assert "ltpu_predict_latency_ms_bucket{le=" in prom
    assert 'le="+Inf"' in prom
    dumps = glob.glob("/tmp/lgbtpu_smoke/flight*.flight.json")
    assert dumps, "flight-recorder smoke left no dump"
    d = json.load(open(dumps[-1]))
    assert d["seam"] == "predict.dispatch"
    assert d["events"]
    # continuous-training probe (round 15): the closed
    # train->evaluate->publish loop — scripts/continuous_probe.py,
    # run in-line by bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/continuous.json") as f:
        ct = json.load(f)
    for field in ("cycles", "rows_ingested", "publishes", "rollbacks",
                  "parity", "rollback_fired", "rollback_parity",
                  "kill_returncode", "byte_identical",
                  "kill_recovery"):
        assert field in ct, f"continuous probe missing {field}"
    assert ct["cycles"] >= 2 and ct["publishes"] >= 2
    # served predictions byte-identical to a direct Booster.predict
    # of the published model, before AND after the auto-rollback
    assert ct["parity"] == "pass"
    assert ct["rollback_fired"] and ct["rollbacks"] >= 1
    assert ct["rollback_parity"] == "pass"
    # the SIGKILL smoke really killed (-9), the cycle resumed from
    # its ledger, and the resumed publish is byte-identical
    assert ct["kill_returncode"] == -9
    assert ct["cycle_resumed_from_ledger"] is True
    assert ct["byte_identical"] is True
    assert ct["kill_recovery"] == "pass"
    # model-quality probe (round 17): profile captured at train,
    # monitors armed from the sidecar at publish, zero drift on
    # in-distribution rows, a shifted stream past threshold with the
    # warn fired, gauges on the Prometheus surface, report CLI
    # agreeing — scripts/quality_probe.py, run in-line by
    # bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/quality.json") as f:
        q = json.load(f)
    for field in ("parity", "profile_features", "in_dist_worst_psi",
                  "shifted_worst_feature", "shifted_worst_psi",
                  "warn_fired", "prom_gauges", "report_cli",
                  "models_quality_block", "sampled_rows"):
        assert field in q, f"quality probe missing {field}"
    assert q["parity"] == "pass"
    # zero drift on in-distribution rows, loud drift on the shift
    assert q["in_dist_worst_psi"] < 0.05
    assert q["shifted_worst_psi"] > 0.2
    assert q["shifted_worst_feature"] == 2
    assert q["warn_fired"] is True
    assert any("worst_feature_psi" in g for g in q["prom_gauges"])
    assert q["report_cli"] == "pass"
    assert q["models_quality_block"] == "pass"
    # serving probe (round 14): concurrent single-row clients through
    # the micro-batching HTTP frontend — scripts/serve_bench.py, run
    # in-line by bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/serve.json") as f:
        s = json.load(f)
    for field in ("requests", "requests_ok", "dispatches",
                  "amortization", "p50_ms", "p99_ms", "shed",
                  "coalesced_requests", "parity", "drain"):
        assert field in s, f"serve probe missing {field}"
    assert s["parity"] == "pass"
    assert not s["failures"]
    # every offered request was either answered or explicitly shed
    # (bounds derived from the run's own totals — SERVE_CLIENTS /
    # SERVE_REQUESTS overrides must not break the assertion)
    assert s["requests_ok"] + s["shed"] >= s["requests"]
    assert s["requests_ok"] >= s["clients"]
    # the tentpole claim: N concurrent single-row requests cost
    # strictly fewer than N dispatches
    assert s["dispatches"] < s["requests"], (
        f"{s['dispatches']} dispatches for {s['requests']} requests "
        "— the micro-batcher coalesced nothing")
    assert s["coalesced_requests"] > 0
    # generous tail bound: the smoke runs on CPU with cold jit
    assert s["p99_ms"] < 30000
    assert s["drain"] == "clean", "serving queues not drained at stop"
    # lane fleet probe (round 20): the same closed-loop load through
    # 1 then 2 simulated lanes over a per-row simulated device wall —
    # the scale-out tentpole gate is 2-lane rows/s >= 1.5x single
    ls = s["lane_scaling"]
    assert ls["parity"] == "pass" and ls["drain"] == "clean"
    assert ls["gate"] == "pass", (
        f"2-lane scaling {ls['scaling_x']}x below the 1.5x gate "
        f"({ls['single_lane_rows_per_s']} -> "
        f"{ls['multi_lane_rows_per_s']} rows/s)")
    assert ls["scaling_x"] >= 1.5
    # co-batching probe (round 20): mixed-model open-loop traffic
    # over one fused program — fused dispatches must be strictly
    # fewer than the per-model dispatches they replaced, at full
    # per-member parity
    mm = s["mixed_model"]
    assert mm["parity"] == "pass" and not mm["failures"]
    assert mm["fused_group"] == ["m0", "m1", "m2"]
    assert mm["cobatch_dispatches"] > 0
    assert mm["cobatch_dispatches"] < mm["cobatch_fused_models"], (
        f"{mm['cobatch_dispatches']} fused dispatches for "
        f"{mm['cobatch_fused_models']} model-dispatches — "
        "co-batching amortized nothing")
    assert mm["cobatch_amortized"] is True
    # trace-overhead probe (round 23): the same load with tracing off
    # vs spans+headers — the p50 delta is the whole per-request cost
    # of context propagation; the in-bench gate bounds it at 25%
    # (generous: CPU smoke jitter dwarfs the microseconds under test;
    # the design target documented in docs/OBSERVABILITY.md is <5%)
    to = s["trace_overhead"]
    assert to["parity"] == "pass"
    assert isinstance(to["overhead_pct"], (int, float))
    assert to["gate"] == "pass", (
        f"tracing p50 overhead {to['overhead_pct']}% "
        f"({to['p50_ms_tracing_off']} -> "
        f"{to['p50_ms_tracing_on']} ms)")
    # distributed-tracing probe (round 23): header round trip over
    # real HTTP, the merged timeline's request->dispatch flow arrow,
    # and the injected stall journaled with seam + trace id —
    # scripts/trace_probe.py, run in-line by bench_smoke.sh
    with open("/tmp/lgbtpu_smoke/trace.json") as f:
        tr = json.load(f)
    for field in ("header_echo", "flow_link", "flow_links",
                  "stall_journal", "journal_instants",
                  "status_overall"):
        assert field in tr, f"trace probe missing {field}"
    assert tr["header_echo"] == "pass"
    assert tr["flow_link"] == "pass" and tr["flow_links"] >= 1
    assert tr["stall_journal"] == "pass"
    assert tr["journal_instants"] >= 1
    assert tr["status_overall"] == "pass"


@pytest.mark.slow
def test_bench_big_time_box_contains_rc124():
    """The r5 rc=124 regression (BENCH_r05.json `parsed: null`): an
    ADMITTED big-scale run that overruns used to blow the outer driver
    timeout and kill the whole bench.  Round 13 runs the big scale in
    a time-boxed subprocess — this pins the containment: a 3s box no
    real training run can meet must degrade to a skip-with-note record
    while the bench still exits rc 0 with its one-line JSON."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", BENCH_CHUNK="1",
        BENCH_ROWS="2048", BENCH_ITERS="2", BENCH_VALID_ROWS="1024",
        BENCH_LEAVES="15", BENCH_MAX_BIN="31",
        BENCH_BIG="1", BENCH_ROWS_BIG="4096", BENCH_ITERS_BIG="2",
        BENCH_BIG_BOX_S="3", BENCH_BUDGET_S="100000",
        BENCH_LTR="0", BENCH_PREDICT="0", BENCH_CONSTRUCT="0",
        BENCH_LOCAL_REF="0", BENCH_SKIP_F32="1",
        BENCH_SLOPE_PROBE="0")
    run = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=420)
    assert run.returncode == 0, (run.stdout or "")[-2000:] + \
        (run.stderr or "")[-2000:]
    lines = [ln for ln in run.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines!r}"
    out = json.loads(lines[0])
    big = next(s for s in out["scales"]
               if s.get("task") == "binary_big")
    assert "skipped" in big, big
    assert "time box" in big["skipped"], big["skipped"]


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
