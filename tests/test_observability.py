"""Distributed-and-production observability tests (round-13 tentpole).

Covers the issue's hard requirements:
- fixed log-bucket histograms: exact counts, Prometheus ``le``
  semantics, p50/p95/p99 derivable (asserted against the serving
  path's real latency histograms),
- Prometheus text export (name mapping, cumulative bucket
  monotonicity, atomic textfile) + the stdlib /metrics + /healthz
  endpoint,
- collective instrumentation: trace-time byte/call counters for the
  explicit collectives and the compiled-HLO scanner that covers the
  sharding-implicit ones (the MULTICHIP gate's numbers as counters),
- step-wall gauges + straggler detector exactness with an injected
  ``time.sleep`` on one simulated host thread,
- cross-host trace shards: per-host export tagged (host_id, run_id),
  clock alignment on the rendezvous mark, one-lane-per-host Perfetto
  validity of the merge tool,
- crash flight recorder: ring bounds, dump triggers (fault seam,
  retry exhaustion, OOM downshift) and dump schema.
"""
import glob
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.reliability.faults import FAULTS
from lightgbm_tpu.telemetry import (DEPTH_BOUNDS, LATENCY_BOUNDS_MS,
                                    TELEMETRY, Telemetry, hist_quantile,
                                    merge_shards)
from lightgbm_tpu.telemetry import main as telemetry_main
from lightgbm_tpu.utils.log import Log


@pytest.fixture(autouse=True)
def _clean_observability():
    level = Log.level
    TELEMETRY.configure("off")
    TELEMETRY.set_fence(False)
    TELEMETRY.reset()
    TELEMETRY.flight.disarm()
    FAULTS.reset()
    yield
    TELEMETRY.configure("off")
    TELEMETRY.set_fence(False)
    TELEMETRY.reset()
    TELEMETRY.flight.disarm()
    TELEMETRY.stop_metrics_server()
    FAULTS.reset()
    Log.set_level(level)


def _train(n=300, iters=4, seed=0, f=6, **params):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] - 0.4 * X[:, 1]
    p = {"objective": "regression", "verbose": -1, "num_leaves": 7,
         "min_data_in_leaf": 5, **params}
    return lgb.train(p, lgb.Dataset(X, label=y), iters,
                     verbose_eval=False), X


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------
def test_histogram_exact_counts_and_le_semantics():
    TELEMETRY.configure("counters")
    # 0.05 sits exactly ON the first bound: le semantics put it there
    for v in (0.03, 0.05, 0.07, 102.4, 1e9):
        TELEMETRY.observe("lat_ms", v)
    h = TELEMETRY.histograms()["lat_ms"]
    assert h["count"] == 5
    assert h["sum"] == pytest.approx(0.03 + 0.05 + 0.07 + 102.4 + 1e9)
    counts = h["counts"]
    bounds = h["bounds"]
    assert bounds == list(LATENCY_BOUNDS_MS)
    assert counts[0] == 2                      # 0.03 and 0.05 (on-bound)
    assert counts[1] == 1                      # 0.07 <= 0.1
    assert counts[bounds.index(102.4)] == 1    # exactly on 102.4
    assert counts[-1] == 1                     # 1e9 -> +Inf overflow
    assert sum(counts) == h["count"]


def test_histogram_quantiles_derivable():
    TELEMETRY.configure("counters")
    # 90 fast (<=0.4ms) + 10 slow (~200ms): p50 in the fast bucket,
    # p95/p99 in the slow one — the serving-tail shape the histograms
    # exist to expose
    for _ in range(90):
        TELEMETRY.observe("q_ms", 0.3)
    for _ in range(10):
        TELEMETRY.observe("q_ms", 150.0)
    h = TELEMETRY.histograms()["q_ms"]
    assert hist_quantile(h, 0.5) == 0.4
    assert hist_quantile(h, 0.95) == 204.8
    assert hist_quantile(h, 0.99) == 204.8
    # empty histogram never divides by zero
    assert hist_quantile({"bounds": [1.0], "counts": [0, 0],
                          "count": 0, "sum": 0}, 0.5) == 0.0


def test_histogram_custom_bounds_and_off_mode():
    TELEMETRY.observe("nope", 1.0)             # off: not recorded
    assert TELEMETRY.histograms() == {}
    TELEMETRY.configure("counters")
    TELEMETRY.observe("depth", 2, bounds=DEPTH_BOUNDS)
    TELEMETRY.observe("depth", 33)             # bounds fixed at first observe
    h = TELEMETRY.histograms()["depth"]
    assert h["bounds"] == list(DEPTH_BOUNDS)
    assert h["counts"][1] == 1 and h["counts"][-1] == 1


# ---------------------------------------------------------------------------
# prometheus export
# ---------------------------------------------------------------------------
def _parse_prom(text):
    metrics = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name, val = ln.rsplit(None, 1)
        metrics.setdefault(name, float(val))
    return metrics


def test_prometheus_text_format():
    TELEMETRY.configure("counters")
    TELEMETRY.add("predict_requests", 7)
    TELEMETRY.gauge("rss_mb_peak", 123.5)
    TELEMETRY.gauge("grower.hist_kernel", "pallas")   # string: skipped
    for v in (0.3, 0.3, 150.0):
        TELEMETRY.observe("predict_latency_ms", v)
    text = TELEMETRY.to_prometheus()
    m = _parse_prom(text)
    assert m["ltpu_predict_requests_total"] == 7
    assert m["ltpu_rss_mb_peak"] == 123.5
    assert not any("hist_kernel" in k for k in m)
    # histogram: cumulative buckets, +Inf == count, sum present
    buckets = [(k, v) for k, v in m.items()
               if k.startswith("ltpu_predict_latency_ms_bucket")]
    assert buckets, text
    vals = [v for _, v in buckets]
    assert vals == sorted(vals), "buckets must be cumulative"
    assert m['ltpu_predict_latency_ms_bucket{le="+Inf"}'] == 3
    assert m["ltpu_predict_latency_ms_count"] == 3
    assert m["ltpu_predict_latency_ms_sum"] == pytest.approx(150.6)
    assert 'ltpu_info{run_id="' in text


def test_write_prom_file(tmp_path):
    TELEMETRY.configure("counters")
    TELEMETRY.add("c", 1)
    path = tmp_path / "metrics" / "ltpu.prom"
    out = TELEMETRY.write_prom(str(path))
    assert out == str(path)
    assert "ltpu_c_total 1" in path.read_text()
    with pytest.raises(ValueError):
        TELEMETRY.write_prom("")


def test_http_metrics_endpoint():
    TELEMETRY.configure("counters")
    TELEMETRY.add("scraped", 3)
    srv = TELEMETRY.serve_metrics(0)           # ephemeral port
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read()
        assert b"ltpu_scraped_total 3" in body
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert health["status"] == "ok"
        assert health["mode"] == "counters"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/other", timeout=10)
        # idempotent: a second call returns the running server
        assert TELEMETRY.serve_metrics(0) is srv
    finally:
        TELEMETRY.stop_metrics_server()


def test_serving_latency_histograms_end_to_end():
    """Acceptance criterion: the Prometheus textfile exposes serving
    latency histograms from which p50/p95/p99 are computable."""
    bst, X = _train(n=220, iters=4, seed=3, f=8)
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    for n in (1, 3, 9, 16, 40):
        bst.predict(X[:n], device=True)
    hists = TELEMETRY.histograms()
    lat = hists["predict_latency_ms"]
    assert lat["count"] == 5
    assert hists["predict_drain_ms"]["count"] >= 5
    depth = hists["predict_queue_depth"]
    assert depth["bounds"] == list(DEPTH_BOUNDS)
    assert depth["count"] >= 5
    d = TELEMETRY.snapshot()["derived"]
    for tag in ("p50", "p95", "p99"):
        assert d[f"predict_latency_{tag}_ms"] > 0
    assert d["predict_latency_p50_ms"] <= d["predict_latency_p99_ms"]
    # and the same numbers are derivable from the prom text alone
    m = _parse_prom(TELEMETRY.to_prometheus())
    cum = [(float(k.split('le="')[1].rstrip('"}'))
            if "+Inf" not in k else float("inf"), v)
           for k, v in m.items()
           if k.startswith("ltpu_predict_latency_ms_bucket")]
    cum.sort()
    total = m["ltpu_predict_latency_ms_count"]
    p50 = next(b for b, c in cum if c >= 0.5 * total)
    assert p50 == d["predict_latency_p50_ms"]


# ---------------------------------------------------------------------------
# collective instrumentation
# ---------------------------------------------------------------------------
def test_collective_trace_counters_exact():
    """Explicit collectives record call count + payload bytes at trace
    time; bytes are exact from the abstract shape."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from lightgbm_tpu.learner.grower import _get_shard_map
    from lightgbm_tpu.parallel.collectives import Collectives

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    comm = Collectives("data")
    shard_map = _get_shard_map()

    def step(x):
        g = comm.all_gather(x)              # (8,) f32 per shard
        y = comm.reduce_scatter(g)          # (64,) f32
        s = comm.allreduce_sum(jnp.sum(x))  # scalar f32
        return y + s

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data")))
    fn.lower(jnp.zeros(64, jnp.float32))    # trace only — no execution
    c = TELEMETRY.counters()
    assert c["collective_allgather_calls"] == 1
    assert c["collective_allgather_bytes"] == 8 * 4       # per-shard view
    assert c["collective_reduce_scatter_calls"] == 1
    assert c["collective_reduce_scatter_bytes"] == 64 * 4
    assert c["collective_allreduce_calls"] == 1
    assert c["collective_allreduce_bytes"] == 4


def test_collective_counters_none_axis_noop():
    from lightgbm_tpu.parallel.collectives import Collectives
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    comm = Collectives(None)
    comm.allreduce_sum(np.ones(4, np.float32))
    comm.all_gather(np.ones(4, np.float32))
    assert not any(k.startswith("collective_")
                   for k in TELEMETRY.counters())


def test_host_collectives_counters():
    from lightgbm_tpu.parallel.collectives import HostCollectives
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    hc = HostCollectives(shards=2)
    shards = [np.ones((4, 3), np.float32)] * 2
    hc.simulate_allreduce(shards)
    hc.simulate_allgather(shards)
    c = TELEMETRY.counters()
    assert c["collective_allreduce_calls"] == 2
    assert c["collective_allreduce_bytes"] == 2 * 4 * 3 * 4
    assert c["collective_allgather_calls"] == 2


def test_scan_and_record_compiled_collectives():
    from lightgbm_tpu.parallel.collectives import (
        record_compiled_collectives, scan_compiled_collectives)
    txt = """\
  %ar = (f32[378]{0}, f32[8192]{0}) all-reduce(f32[378] %a, f32[8192] %b), replica_groups={}
  %rs = u8[1024]{0} reduce-scatter(u8[8192] %c), dimensions={0}
  %ag = f32[4096]{0} all-gather-start(f32[512] %d), dimensions={0}
  %no = f32[4096]{0} add(f32[4096] %e, f32[4096] %f)
"""
    st = scan_compiled_collectives(txt)
    assert st["kinds"]["all-reduce"] == {"count": 1,
                                         "bytes": (378 + 8192) * 4}
    assert st["kinds"]["reduce-scatter"] == {"count": 1, "bytes": 1024}
    assert st["kinds"]["all-gather"] == {"count": 1, "bytes": 4096 * 4}
    assert st["largest_reduce_bytes"] == (378 + 8192) * 4
    assert st["reduce_count"] == 2
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    record_compiled_collectives(txt, program="unit")
    c = TELEMETRY.counters()
    g = TELEMETRY.gauges()
    assert c["hlo_collective_all_reduce_bytes"] == (378 + 8192) * 4
    assert c["hlo_collective_reduce_scatter_count"] == 1
    assert g["collective_largest_reduce_bytes"] == (378 + 8192) * 4
    assert g["collective_reduce_count"] == 2
    assert "all-gather:1x" in g["collective_profile.unit"]


def test_mesh_topology_gauges():
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.mesh import ShardingPolicy, build_mesh

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    cfg = Config.from_params({"tree_learner": "data", "verbose": -1})
    mesh = build_mesh(cfg)
    assert mesh is not None
    ShardingPolicy(cfg, mesh)
    g = TELEMETRY.gauges()
    assert g["mesh_devices"] == len(jax.devices())
    assert g["mesh_hosts"] == 1
    assert g["mesh_axes"] == f"data={len(jax.devices())}"


# ---------------------------------------------------------------------------
# step wall + straggler detector
# ---------------------------------------------------------------------------
def test_step_wall_stats_exact():
    from lightgbm_tpu.parallel.monitor import step_wall_stats
    st = step_wall_stats([0.1, 0.1, 0.3])
    assert st["max"] == 0.3 and st["min"] == 0.1
    assert st["mean"] == pytest.approx(0.5 / 3)
    assert st["ratio"] == pytest.approx(0.3 / (0.5 / 3))
    with pytest.raises(ValueError):
        step_wall_stats([])


def test_straggler_ratio_with_injected_sleep():
    """The issue's exactness requirement: 4 simulated host threads
    each time their own step, one sleeps ~4x longer; the gauges must
    equal step_wall_stats over the gathered walls EXACTLY, and the
    slow host must trip the straggler counter."""
    from lightgbm_tpu.parallel import monitor

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    monitor._warned["straggler"] = False
    n_hosts = 4
    barrier = threading.Barrier(n_hosts)
    walls = [None] * n_hosts
    results = [None] * n_hosts

    def gather_for(host):
        def gather(seconds):
            walls[host] = seconds
            barrier.wait(timeout=30)     # the allgather rendezvous
            barrier.wait(timeout=30)     # everyone has published
            return list(walls)
        return gather

    def host_thread(host):
        t0 = time.perf_counter()
        time.sleep(0.2 if host == 2 else 0.05)   # host 2 straggles
        results[host] = monitor.record_step_wall(
            time.perf_counter() - t0, gather=gather_for(host))

    threads = [threading.Thread(target=host_thread, args=(h,))
               for h in range(n_hosts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    from lightgbm_tpu.parallel.monitor import step_wall_stats
    expect = step_wall_stats(walls)
    for st in results:
        assert st == expect              # identical derivation per host
    g = TELEMETRY.gauges()
    assert g["step_wall_ms_max"] == round(expect["max"] * 1e3, 3)
    assert g["step_wall_ms_min"] == round(expect["min"] * 1e3, 3)
    assert g["step_wall_ms_mean"] == round(expect["mean"] * 1e3, 3)
    assert g["straggler_ratio"] == round(expect["ratio"], 4)
    assert expect["ratio"] > 1.5         # the injected sleep shows up
    assert TELEMETRY.counters()["straggler_steps"] >= 1
    assert TELEMETRY.histograms()["step_wall_hist_ms"]["count"] \
        == n_hosts


def test_record_step_wall_single_host():
    from lightgbm_tpu.parallel.monitor import record_step_wall
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    assert record_step_wall(0.01) is None      # nothing to compare
    g = TELEMETRY.gauges()
    assert g["step_wall_ms"] == 10.0
    assert "straggler_ratio" not in g
    TELEMETRY.configure("off")
    assert record_step_wall(0.01) is None      # off: no-op


def test_prometheus_no_gauge_histogram_family_collision():
    """One Prometheus metric name cannot be declared both gauge and
    histogram — the exposition the scrapers reject.  Drive the two
    code paths that used to collide (step wall, host allgather) and
    assert every family name is declared exactly once."""
    from lightgbm_tpu.parallel.monitor import record_step_wall
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    record_step_wall(0.01, gather=lambda s: [s, 2 * s])
    TELEMETRY.add("collective_host_allgather_bytes", 1024)
    TELEMETRY.observe("collective_host_allgather_ms", 0.4)
    types = {}
    for ln in TELEMETRY.to_prometheus().splitlines():
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split()
            assert name not in types, \
                f"{name} declared {types[name]} AND {kind}"
            types[name] = kind
    assert types["ltpu_step_wall_ms"] == "gauge"
    assert types["ltpu_step_wall_hist_ms"] == "histogram"
    assert types["ltpu_collective_host_allgather_ms"] == "histogram"
    assert types["ltpu_collective_host_allgather_bytes_total"] \
        == "counter"


# ---------------------------------------------------------------------------
# cross-host trace shards + merge
# ---------------------------------------------------------------------------
def _make_shard(tmp_path, host, t_skew_s, run_id="runx"):
    """Simulate one host's telemetry lifetime and export its shard.
    ``t_skew_s`` shifts this host's clock: its rendezvous mark lands
    later on its own (relative) timeline, which is exactly what the
    merge must undo."""
    tm = Telemetry()
    tm.run_id = run_id
    tm.host_id = host
    tm.configure("spans")
    if t_skew_s:
        time.sleep(t_skew_s)
    tm.mark_sync("rendezvous")
    with tm.span("train_chunk", iters=2):
        time.sleep(0.01)
    tm.add("trees_dispatched", 2)
    jsonl, _ = tm.export(str(tmp_path / "run"), shard=True)
    assert jsonl.endswith(f".host{host}.jsonl")
    return jsonl


def test_shard_export_tags_host_and_run(tmp_path):
    shard = _make_shard(tmp_path, 3, 0.0)
    lines = [json.loads(ln) for ln in open(shard)]
    meta = lines[0]
    assert meta["type"] == "meta"
    assert meta["host_id"] == 3
    assert meta["run_id"] == "runx"
    assert meta["sync_name"] == "rendezvous"
    assert meta["sync_ts_us"] >= 0
    assert lines[-1]["type"] == "snapshot"
    assert lines[-1]["host_id"] == 3
    names = {ln["name"] for ln in lines if ln.get("type") == "span"}
    assert {"rendezvous", "train_chunk"} <= names


def test_merge_aligns_clocks_one_lane_per_host(tmp_path):
    # host 1 "starts" 50ms later and host 2 100ms later than host 0:
    # without alignment their spans would sit at different offsets
    shards = [_make_shard(tmp_path, h, skew)
              for h, skew in ((0, 0.0), (1, 0.05), (2, 0.10))]
    merged = merge_shards(shards)
    evs = merged["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {0, 1, 2}
    # one process_name lane per host, sort order by host id
    lanes = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert lanes == {0: "host 0", 1: "host 1", 2: "host 2"}
    # clock alignment: the rendezvous marks coincide after the shift
    sync_ts = {e["pid"]: e["ts"] for e in evs
               if e["ph"] == "X" and e["name"] == "rendezvous"}
    assert len(sync_ts) == 3
    spread = max(sync_ts.values()) - min(sync_ts.values())
    assert spread < 1.0, f"sync marks {spread}us apart after alignment"
    assert not merged["metadata"].get("unaligned")
    assert merged["metadata"]["hosts"] == [0, 1, 2]
    # per-host counters survive as counter tracks
    assert any(e["ph"] == "C" and e["name"] == "trees_dispatched"
               and e["pid"] == 2 for e in evs)


def test_merge_cli_and_missing_sync(tmp_path, capsys):
    s0 = _make_shard(tmp_path, 0, 0.0)
    # a shard WITHOUT a sync mark (pre-rendezvous crash): merges with
    # zero shift and is reported, not dropped
    tm = Telemetry()
    tm.run_id = "runx"
    tm.host_id = 1
    tm.configure("spans")
    with tm.span("binning"):
        pass
    s1, _ = tm.export(str(tmp_path / "run"), shard=True)
    out = str(tmp_path / "merged.perfetto.json")
    rc = telemetry_main(["merge", "-o", out, s0, s1])
    assert rc == 0
    merged = json.load(open(out))          # valid JSON on disk
    assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}
    assert merged["metadata"]["unaligned"] == [s1]
    assert "merged 2 shard(s), 2 host lane(s)" in capsys.readouterr().out
    # usage errors: rc 2
    assert telemetry_main([]) == 2
    assert telemetry_main(["merge"]) == 2
    assert telemetry_main(["merge", str(tmp_path / "absent.jsonl")]) == 2


# ---------------------------------------------------------------------------
# crash flight recorder
# ---------------------------------------------------------------------------
def test_flight_ring_bounded_and_dump_schema(tmp_path):
    TELEMETRY.configure("spans")
    fl = TELEMETRY.flight
    fl.arm(str(tmp_path / "fl"))
    for i in range(600):                   # > ring capacity of 512
        TELEMETRY.add("burst", 1)
    with TELEMETRY.span("train_chunk"):
        pass
    Log.set_level(0)          # the sink sees only EMITTED lines —
    Log.warning("something odd")  # suite order must not mute this
    path = fl.dump("manual_test", seam="gbdt.train_chunk", note=7)
    d = json.load(open(path))
    assert d["reason"] == "manual_test"
    assert d["seam"] == "gbdt.train_chunk"
    assert d["note"] == 7
    assert d["run_id"] == TELEMETRY.run_id
    assert len(d["events"]) <= 512
    kinds = {e["kind"] for e in d["events"]}
    assert {"counter", "span", "log"} <= kinds
    assert any(e["kind"] == "log" and "something odd" in
               e["detail"]["msg"] for e in d["events"])
    assert d["counters"]["burst"] == 600
    # disarmed: dump is a no-op returning None
    fl.disarm()
    assert fl.dump("after_disarm") is None


def test_flight_dump_on_fault_seam(tmp_path):
    TELEMETRY.configure("counters")
    TELEMETRY.flight.arm(str(tmp_path / "fl"))
    FAULTS.configure("native.entry:1:RuntimeError")
    with pytest.raises(RuntimeError):
        FAULTS.fault_point("native.entry")
    dumps = glob.glob(str(tmp_path / "fl-*.flight.json"))
    assert len(dumps) == 1
    d = json.load(open(dumps[0]))
    assert d["reason"] == "fault:RuntimeError"
    assert d["seam"] == "native.entry"
    assert d["call"] == 1


def test_flight_dump_on_retry_exhaustion(tmp_path):
    from lightgbm_tpu.reliability.retry import RetryPolicy, retry_call
    TELEMETRY.flight.arm(str(tmp_path / "fl"))

    def always_transient():
        raise ConnectionError("connection reset by peer")

    with pytest.raises(ConnectionError):
        retry_call(always_transient, seam="gbdt.train_chunk",
                   policy=RetryPolicy(max_retries=1, base_delay_s=0),
                   sleep=lambda s: None)
    dumps = glob.glob(str(tmp_path / "fl-*.flight.json"))
    assert len(dumps) == 1
    d = json.load(open(dumps[0]))
    assert d["reason"] == "retry_exhausted"
    assert d["seam"] == "gbdt.train_chunk"
    assert d["attempts"] == 2


def test_flight_dump_on_serving_oom_downshift(tmp_path):
    """The OOM ladder keeps serving alive AND leaves a flight dump
    explaining what degraded."""
    bst, X = _train(n=150, iters=3, seed=5)
    host = bst.predict(X[:20], device=False)
    TELEMETRY.configure("counters")
    TELEMETRY.flight.arm(str(tmp_path / "fl"))
    FAULTS.configure("predict.dispatch:1:oom")
    out = bst.predict(X[:20], device=True)     # downshifts, succeeds
    np.testing.assert_allclose(out, host, rtol=1e-5, atol=1e-7)
    dumps = sorted(glob.glob(str(tmp_path / "fl-*.flight.json")))
    reasons = {json.load(open(p))["reason"] for p in dumps}
    assert "oom_downshift" in reasons
    oom = next(json.load(open(p)) for p in dumps
               if json.load(open(p))["reason"] == "oom_downshift")
    assert oom["seam"] == "predict.dispatch"
    assert oom["new_cap"] >= 1
    assert TELEMETRY.counters()["oom_downshifts"] == 1


def test_flight_recorder_config_knobs(tmp_path):
    from lightgbm_tpu.config import Config
    Config.from_params({"verbose": -1,
                        "flight_recorder_out": str(tmp_path / "fr"),
                        "telemetry_prom_out": str(tmp_path / "m.prom")})
    assert TELEMETRY.flight.armed
    assert TELEMETRY.prom_out == str(tmp_path / "m.prom")
    # a later default-valued Config must not disarm either
    Config.from_params({"verbose": -1})
    assert TELEMETRY.flight.armed
    assert TELEMETRY.prom_out == str(tmp_path / "m.prom")
    TELEMETRY.prom_out = ""


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"]))
