"""histogram_pool_size governance (reference config.h:216 + the LRU
HistogramPool, feature_histogram.hpp:653-823): over-budget configs drop
histogram subtraction and compute both children directly."""
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.learner.grower import TreeGrower


def _task(n=1500, f=12, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    return X, y


def test_pool_size_disables_cache():
    X, y = _task()
    cfg = Config.from_params({"objective": "binary", "verbose": -1,
                              "num_leaves": 31,
                              "histogram_pool_size": 0.001})
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    assert not g.use_hist_cache
    cfg2 = Config.from_params({"objective": "binary", "verbose": -1,
                               "num_leaves": 31})
    g2 = TreeGrower(core, cfg2)
    assert g2.use_hist_cache


def test_no_cache_mode_trains_equivalently():
    """Direct-both-children mode must produce the same trees up to
    float summation order (subtraction vs direct accumulation)."""
    X, y = _task()
    base = {"objective": "binary", "verbose": -1, "num_leaves": 15,
            "min_data_in_leaf": 5}
    b1 = lgb.train(base, lgb.Dataset(X, label=y), 8, verbose_eval=False)
    b2 = lgb.train(dict(base, histogram_pool_size=0.001),
                   lgb.Dataset(X, label=y), 8, verbose_eval=False)
    p1, p2 = b1.predict(X), b2.predict(X)
    assert np.abs(p1 - p2).mean() < 1e-3
    assert (((p1 > 0.5) == (p2 > 0.5)).mean()) > 0.995


def test_wide_config_trains_with_bounded_cache():
    """A wide config (many features x 255 bins x 255 leaves) whose
    cache would be large trains under an explicit budget with the
    (1, G, B, 3) dummy cache."""
    rng = np.random.RandomState(1)
    X = rng.randn(800, 100)
    y = (X[:, 0] > 0).astype(float)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 255,
              "max_bin": 255, "histogram_pool_size": 8.0,
              "min_data_in_leaf": 2}
    cfg = Config.from_params(params)
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = TreeGrower(core, cfg)
    assert not g.use_hist_cache
    bst = lgb.train(params, lgb.Dataset(X, label=y), 3,
                    verbose_eval=False)
    assert (((bst.predict(X) > 0.5) == y).mean()) > 0.95
