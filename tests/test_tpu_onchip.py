"""REAL-CHIP Pallas kernel parity — the analog of the reference's
GPU_DEBUG_COMPARE CPU-vs-GPU histogram comparator
(gpu_tree_learner.cpp:1020-1044).  The interpret-mode tests in
test_histogram_kernel.py pin kernel SEMANTICS on CPU; these pin the
Mosaic-compiled numerics on actual TPU hardware.  Skipped on CPU CI;
run manually on a chip (`LGBM_TPU_ONCHIP=1 pytest tests/test_tpu_onchip.py`
— the env var stops conftest from forcing the CPU backend); last
recorded run in PARITY.md.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon"):
    pytest.skip("needs a real TPU chip", allow_module_level=True)

from lightgbm_tpu.ops.histogram import (  # noqa: E402
    compute_group_histograms, compute_group_histograms_fused,
    compute_group_histograms_pallas, compute_group_histograms_q_packed,
    precompute_bin_onehot, quantize_gradients)
from lightgbm_tpu.ops.partition import (apply_route_table,  # noqa: E402
                                        build_route_table)


@pytest.fixture(scope="module")
def case():
    rng = np.random.RandomState(0)
    N, G, B, L = 8192, 12, 63, 31
    bins = jnp.asarray(rng.randint(0, B, (N, G)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(np.abs(rng.randn(N)).astype(np.float32))
    cnt = jnp.asarray((rng.rand(N) > 0.2).astype(np.float32))
    leaf = jnp.asarray(rng.randint(-1, L, N).astype(np.int32))
    ref = compute_group_histograms(bins, grad, hess, cnt, leaf,
                                   num_leaves=L, max_group_bin=B,
                                   compute_dtype="float32", chunk=8192)
    return bins, grad, hess, cnt, leaf, ref, (N, G, B, L)


def _close(ref, got, tol=5e-3):
    scale = float(jnp.max(jnp.abs(ref))) + 1.0
    return float(jnp.max(jnp.abs(ref - got))) / scale < tol


def test_onchip_pallas_expansion_kernel(case):
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    got = compute_group_histograms_pallas(
        bins, grad, hess, cnt, leaf, num_leaves=L, max_group_bin=B,
        block=1024)
    assert _close(ref, got)
    # count channel exact (0/1 weights are bf16-exact)
    assert float(jnp.max(jnp.abs(ref[..., 2] - got[..., 2]))) == 0.0


def test_onchip_quantized_packed_kernel(case):
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    wq, scales = quantize_gradients(grad, hess, cnt)
    slots = jnp.arange(31, dtype=jnp.int32)
    got = compute_group_histograms_q_packed(
        bins, wq, scales, leaf, slots, max_group_bin=B, block=1024)
    # int8 quantization: tolerance = quantization step * sqrt(rows/leaf)
    assert _close(ref, got[:31], tol=2e-2)
    assert float(jnp.max(jnp.abs(ref[..., 2] - got[:31, ..., 2]))) == 0.0


def test_onchip_fused_route_hist(case):
    """Fused kernel on chip: routing BIT-IDENTICAL to the XLA router,
    histogram within bf16 operand tolerance."""
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    rng = np.random.RandomState(1)
    sm = np.zeros(L, bool)
    sm[:6] = True
    tab = build_route_table(
        jnp.asarray(sm),
        jnp.asarray(rng.randint(0, G, L).astype(np.int32)),
        jnp.zeros(L, jnp.int32), jnp.full(L, B, jnp.int32),
        jnp.zeros(L, jnp.int32), jnp.full(L, B - 1, jnp.int32),
        jnp.asarray(np.array([0, 1] * 15 + [0], bool)),
        jnp.asarray(rng.randint(0, B, L).astype(np.int32)),
        jnp.asarray(rng.rand(L) > 0.5),
        jnp.asarray(rng.randint(0, 3, L).astype(np.int32)),
        jnp.asarray(rng.randint(0, 4, L).astype(np.int32)),
        jnp.full(L, B, jnp.int32),
        jnp.asarray(rng.rand(L, B) > 0.5),
        jnp.asarray((np.arange(L) + 40).astype(np.int32)))
    want_leaf = apply_route_table(bins, leaf, tab)
    want = compute_group_histograms(
        bins, grad, hess, cnt, want_leaf, num_leaves=128,
        max_group_bin=B, compute_dtype="float32", chunk=8192)

    ohb = precompute_bin_onehot(bins, max_group_bin=B)
    wT = jnp.stack([grad, hess, cnt], axis=0)
    slots = jnp.arange(42, dtype=jnp.int32)
    got_hist, got_leaf = compute_group_histograms_fused(
        ohb, jnp.asarray(np.asarray(bins).T), wT, None, leaf, tab,
        slots, max_group_bin=B, block=1024, strips=1, quant=False)
    np.testing.assert_array_equal(np.asarray(got_leaf),
                                  np.asarray(want_leaf))
    assert _close(want[:42], got_hist)


def test_onchip_q_tiled_kernel(case):
    """Tiled-iota kernel (the r4+ DEFAULT quantized path,
    learner/grower.py _hist_kernel_q_tiled): int32 accumulation is
    exact, so it must match the int8-quantized reference exactly."""
    from lightgbm_tpu.ops.histogram import compute_group_histograms_q_tiled
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    wq, scales = quantize_gradients(grad, hess, cnt)
    slots = jnp.arange(31, dtype=jnp.int32)
    want = compute_group_histograms_q_packed(
        bins, wq, scales, leaf, slots, max_group_bin=B, block=1024)
    for block in (2048, 8192):
        got = compute_group_histograms_q_tiled(
            jnp.asarray(np.asarray(bins).T), wq.T, scales, leaf, slots,
            max_group_bin=B, block=block, strips=1)
        np.testing.assert_array_equal(np.asarray(want),
                                      np.asarray(got), err_msg=str(block))
    # quantized-vs-f32 tolerance against the float reference
    assert _close(ref, got[:31], tol=2e-2)


def test_onchip_seg_tiled_kernel(case):
    """Leaf-partitioned segment kernel (r6, gated off by default):
    Mosaic must accept the scalar-prefetched block map + dynamic
    sublane accumulate, and the int32 accumulation must match the
    slot-packed tiled kernel exactly.  This is the one-flag A/B the
    r6 rejection record defers to chip-having sessions
    (docs/PARTITION_DESIGN.md)."""
    from lightgbm_tpu.ops.histogram import (
        compute_group_histograms_q_tiled,
        compute_group_histograms_seg_tiled)
    from lightgbm_tpu.ops.partition import (apply_partition,
                                            build_leaf_partition)
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    wq, scales = quantize_gradients(grad, hess, cnt)
    slots = jnp.arange(31, dtype=jnp.int32)
    binsT = jnp.asarray(np.asarray(bins).T)
    want = compute_group_histograms_q_tiled(
        binsT, wq.T, scales, leaf, slots, max_group_bin=B, block=1024,
        strips=1)
    perm, blk_leaf, _ = build_leaf_partition(leaf, num_slots=L,
                                             block=512)
    binsT_p = apply_partition(binsT, perm, axis=1)
    wT_p = apply_partition(wq.T, perm, axis=1)
    inv = jnp.full(L + 1, -1, jnp.int32).at[slots].set(
        jnp.arange(slots.shape[0], dtype=jnp.int32))
    blk_slot = jnp.where(blk_leaf >= 0,
                         inv[jnp.clip(blk_leaf, 0, L)], -1)
    got = compute_group_histograms_seg_tiled(
        binsT_p, wT_p, scales, blk_slot, num_out=31, max_group_bin=B,
        block=512)
    np.testing.assert_array_equal(np.asarray(want)[:31],
                                  np.asarray(got))


def test_onchip_fused_tiled_kernel(case):
    """Fused route + tiled-iota kernel — the kernel the DEFAULT
    training path actually executes every round (grower run():
    use_tiled branch).  Routing bit-identical to the XLA router;
    histogram identical to the non-fused tiled kernel after routing."""
    from lightgbm_tpu.ops.histogram import (
        compute_group_histograms_fused_tiled,
        compute_group_histograms_q_tiled)
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    rng = np.random.RandomState(1)
    sm = np.zeros(L, bool)
    sm[:6] = True
    tab = build_route_table(
        jnp.asarray(sm),
        jnp.asarray(rng.randint(0, G, L).astype(np.int32)),
        jnp.zeros(L, jnp.int32), jnp.full(L, B, jnp.int32),
        jnp.zeros(L, jnp.int32), jnp.full(L, B - 1, jnp.int32),
        jnp.asarray(np.array([0, 1] * 15 + [0], bool)),
        jnp.asarray(rng.randint(0, B, L).astype(np.int32)),
        jnp.asarray(rng.rand(L) > 0.5),
        jnp.asarray(rng.randint(0, 3, L).astype(np.int32)),
        jnp.asarray(rng.randint(0, 4, L).astype(np.int32)),
        jnp.full(L, B, jnp.int32),
        jnp.asarray(rng.rand(L, B) > 0.5),
        jnp.asarray((np.arange(L) + 40).astype(np.int32)))
    want_leaf = apply_route_table(bins, leaf, tab)
    wq, scales = quantize_gradients(grad, hess, cnt)
    slots = jnp.arange(42, dtype=jnp.int32)
    want = compute_group_histograms_q_tiled(
        jnp.asarray(np.asarray(bins).T), wq.T, scales, want_leaf, slots,
        max_group_bin=B, block=2048, strips=1)
    for strips in (1, 2):
        s = jnp.arange(42 * strips, dtype=jnp.int32)
        got_hist, got_leaf = compute_group_histograms_fused_tiled(
            jnp.asarray(np.asarray(bins).T), wq.T, scales, leaf, tab, s,
            max_group_bin=B, block=2048, strips=strips)
        np.testing.assert_array_equal(np.asarray(got_leaf),
                                      np.asarray(want_leaf),
                                      err_msg=str(strips))
        np.testing.assert_array_equal(np.asarray(want),
                                      np.asarray(got_hist)[:42],
                                      err_msg=str(strips))


def test_onchip_route_apply_tiled(case):
    """Pallas exit-route kernel (the r5 DEFAULT tree-exit path):
    (new_leaf, row_value) bit-identical to the XLA apply_route_table
    on chip."""
    from lightgbm_tpu.ops.histogram import route_apply_tiled
    bins, grad, hess, cnt, leaf, ref, (N, G, B, L) = case
    rng = np.random.RandomState(2)
    sm = np.zeros(L, bool)
    sm[:8] = True
    tab = build_route_table(
        jnp.asarray(sm),
        jnp.asarray(rng.randint(0, G, L).astype(np.int32)),
        jnp.zeros(L, jnp.int32), jnp.full(L, B, jnp.int32),
        jnp.zeros(L, jnp.int32), jnp.full(L, B - 1, jnp.int32),
        jnp.asarray(np.array([0, 1] * 15 + [1], bool)),
        jnp.asarray(rng.randint(0, B, L).astype(np.int32)),
        jnp.asarray(rng.rand(L) > 0.5),
        jnp.asarray(rng.randint(0, 3, L).astype(np.int32)),
        jnp.asarray(rng.randint(0, 4, L).astype(np.int32)),
        jnp.full(L, B, jnp.int32),
        jnp.asarray(rng.rand(L, B) > 0.5),
        jnp.asarray((np.arange(L) + 40).astype(np.int32)))
    values = jnp.asarray(rng.randn(L).astype(np.float32) * 2)
    want_leaf, want_val = apply_route_table(bins, leaf, tab,
                                            values=values)
    got_leaf, got_val = route_apply_tiled(
        jnp.asarray(np.asarray(bins).T), leaf, tab, values, block=2048)
    np.testing.assert_array_equal(np.asarray(got_leaf),
                                  np.asarray(want_leaf))
    np.testing.assert_array_equal(np.asarray(got_val),
                                  np.asarray(want_val))
