"""Serving fleet scale-out (lane pool + co-batching + binary wire).

Pins the fleet contracts (docs/SERVING.md):

- predictions through 1, 2 and 4 simulated lanes are byte-identical
  to a direct ``Booster.predict`` of the same rows — lane routing,
  work stealing and the fleet batch split never touch values;
- the router steals from a deep candidate to the shallowest healthy
  lane (``serve_steals``), and per-lane accounting lands in
  ``serve_lane_dispatches`` / the ``GET /models`` ``_fleet`` block;
- a wedged lane browns out ALONE: its in-flight batch stall-fails
  (503 material), the router excludes it, survivors keep answering,
  and only an all-lane stall fails the fleet;
- co-batched mixed-model traffic (``serve_cobatch=on``) answers each
  request byte-identically to that model's solo predict, with fused
  dispatches strictly fewer than the per-model dispatches they
  replaced; membership rebuilds across hot swaps;
- the zero-copy binary frame (``application/x-ltpu-f32`` in,
  ``application/x-ltpu-f64`` out) round-trips exact float64 scores,
  and a malformed frame is a 400, not a batch poison.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.reliability.faults import FAULTS
from lightgbm_tpu.reliability.watchdog import StallError
from lightgbm_tpu.serving import (BINARY_F32, BINARY_F64, LanePool,
                                  MicroBatcher, ModelRegistry,
                                  ServingFrontend, cobatch_key,
                                  parse_binary_rows, resolve_lanes)
from lightgbm_tpu.telemetry import TELEMETRY


def _train(f=6, leaves=15, iters=4, n=200, seed=0, label_col=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, label_col] - 0.4 * X[:, (label_col + 1) % f]
    p = {"objective": "regression", "verbose": -1,
         "num_leaves": leaves, "min_data_in_leaf": 5}
    return lgb.train(p, lgb.Dataset(X, label=y), iters,
                     verbose_eval=False)


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    """Two compatible model files (same feature width, different
    ensembles) — file-loaded publishes route the level-descent
    predictor, the path lanes and co-batching replicate."""
    d = tmp_path_factory.mktemp("fleet_models")
    pa, pb = str(d / "a.txt"), str(d / "b.txt")
    _train(seed=0).save_model(pa)
    _train(seed=1, label_col=2, iters=6).save_model(pb)
    return pa, pb


def _cfg(**over):
    base = {"verbose": -1, "serve_batch_deadline_ms": 5.0,
            "predict_warm_buckets": (1, 8)}
    base.update(over)
    return Config.from_params(base)


@pytest.fixture(autouse=True)
def _telemetry():
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    yield
    FAULTS.reset()
    TELEMETRY.stop_metrics_server()


# ---------------------------------------------------------------------------
# lane resolution
# ---------------------------------------------------------------------------
def test_resolve_lanes_auto_is_single_on_host_backend():
    n, devices = resolve_lanes(_cfg())
    assert n == 1 and devices == [None]


def test_resolve_lanes_explicit_simulated():
    n, devices = resolve_lanes(_cfg(serve_lanes="4"))
    assert n == 4
    # one local device: lanes are unpinned (shared compiled programs)
    assert devices == [None] * 4


def test_serve_lanes_validation():
    with pytest.raises(ValueError, match="serve_lanes"):
        _cfg(serve_lanes="0")
    with pytest.raises(ValueError, match="serve_lanes"):
        _cfg(serve_lanes="sideways")
    with pytest.raises(ValueError, match="serve_cobatch"):
        _cfg(serve_cobatch="maybe")


# ---------------------------------------------------------------------------
# lane parity: N lanes == direct predict, byte for byte
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("lanes", [1, 2, 4])
def test_lane_parity_byte_identical(model_files, lanes):
    pa, _ = model_files
    reg = ModelRegistry(_cfg(serve_lanes=str(lanes)))
    try:
        entry = reg.publish("m", pa, predict_kwargs={"device": True})
        if lanes == 1:
            assert reg.pool is None      # 1 lane == inline dispatch
        else:
            assert reg.pool is not None
            assert reg.pool.n_lanes == lanes
        rng = np.random.RandomState(7)
        batches = [rng.randn(1 + i % 4, 6) for i in range(12)]
        results = {}

        def client(i):
            _, out = reg.predict("m", batches[i])
            results[i] = out

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(batches))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert len(results) == len(batches)
        for i, rows in enumerate(batches):
            ref = entry.booster.predict(rows, device=True)
            assert np.array_equal(results[i], ref), f"batch {i}"
        if lanes > 1:
            c = TELEMETRY.counters()
            assert c.get("serve_lane_dispatches", 0) >= 1
            assert c.get("serve_lane_dispatches", 0) == \
                c.get("serve_dispatches", 0)
    finally:
        reg.close()


def test_fleet_splits_backlog_across_lanes(model_files):
    """With a pool, one coalescing window splits its backlog into
    per-lane shares instead of one greedy batch — the mechanism the
    2-lane throughput gate measures."""
    cfg = _cfg(serve_lanes="2", serve_batch_deadline_ms=30.0)
    reg = ModelRegistry(cfg)
    try:
        entry = reg.publish("m", model_files[0],
                            predict_kwargs={"device": True})
        rng = np.random.RandomState(3)
        barrier = threading.Barrier(8)
        results = {}

        def client(i):
            rows = rng_rows[i]
            barrier.wait(10)
            _, out = reg.predict("m", rows)
            results[i] = out

        rng_rows = [rng.randn(1, 6) for _ in range(8)]
        ts = [threading.Thread(target=client, args=(i,))
              for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert len(results) == 8
        for i in range(8):
            assert np.array_equal(
                results[i],
                entry.booster.predict(rng_rows[i], device=True))
        # 8 requests entering one 30ms window must NOT collapse into
        # a single dispatch: the fleet share caps each batch at
        # ceil(pending/2), so at least 2 dispatches happen
        assert TELEMETRY.counters().get("serve_dispatches", 0) >= 2
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# lane pool unit: routing, stealing, stall isolation
# ---------------------------------------------------------------------------
def _wait_inflight(pool, lane, timeout=10.0):
    import time as _t
    end = _t.monotonic() + timeout
    while _t.monotonic() < end:
        with pool._lock:
            if lane.inflight:
                return
        _t.sleep(0.005)
    raise AssertionError("lane never picked its job up")


def _wait_others_idle(pool, busy, timeout=10.0):
    import time as _t
    end = _t.monotonic() + timeout
    while _t.monotonic() < end:
        with pool._lock:
            if all(ln.depth() == 0
                   for ln in pool._lanes if ln is not busy):
                return
        _t.sleep(0.005)
    raise AssertionError("idle lanes never drained")


def test_lanepool_round_robin_and_steal():
    pool = LanePool([None, None], max_inflight=4)
    try:
        gate0 = threading.Event()

        # rr: first two submits alternate lanes 0, 1
        l0 = pool.submit(lambda lane: gate0.wait(30), lambda e: None)
        l1 = pool.submit(lambda lane: gate0.wait(30), lambda e: None)
        assert {l0.index, l1.index} == {0, 1}
        gate0.set()
        assert pool.drain(30)
        # wedge exactly one lane, then keep submitting instant jobs:
        # whenever the rr candidate lands on the wedged lane (depth 1
        # vs the idle lane's 0) the router must steal to the idle one
        wedge = threading.Event()
        wl = pool.submit(lambda lane: wedge.wait(30), lambda e: None)
        _wait_inflight(pool, wl)
        before = TELEMETRY.counters().get("serve_steals", 0)
        for _ in range(4):
            _wait_others_idle(pool, wl)
            got = pool.submit(lambda lane: None, lambda e: None)
            # NOTHING routes to the wedged lane while an idle
            # neighbor exists: rr candidates on it are stolen away
            assert got.index != wl.index
        assert TELEMETRY.counters().get("serve_steals", 0) > before
        wedge.set()
        assert pool.drain(30)
        snap = pool.snapshot()
        assert [s["lane"] for s in snap] == [0, 1]
        assert sum(s["dispatches"] for s in snap) == 0  # batcher-owned
    finally:
        pool.close()


def test_lanepool_stall_isolation_and_fleet_brownout():
    pool = LanePool([None, None], max_inflight=4)
    try:
        wedge = threading.Event()
        wl = pool.submit(lambda lane: wedge.wait(30), lambda e: None)
        _wait_inflight(pool, wl)
        aborted = []
        # queue a second batch behind the wedged one on the SAME lane
        with pool._lock:
            wl.jobs.append((lambda lane: None,
                            lambda e: aborted.append(e)))
        err = StallError("serve_dispatch(test)", "predict.dispatch",
                         0.1, 0.2)
        n = pool.mark_stalled(wl, err)
        assert n == 1 and aborted == [err]    # queued job 503'd now
        assert pool.healthy_count() == 1
        snap = {s["lane"]: s for s in pool.snapshot()}
        assert snap[wl.index]["stalled"] is True
        assert snap[wl.index]["stalls"] == 1
        # routing excludes the wedged lane from now on
        for _ in range(4):
            assert pool.submit(lambda lane: None,
                               lambda e: None).index != wl.index
        assert TELEMETRY.counters().get("serve_lane_stalls", 0) == 1
        # second stall: the fleet is dead — submit itself raises
        other = next(ln for ln in pool._lanes if ln is not wl)
        pool.mark_stalled(other, err)
        with pytest.raises(StallError):
            pool.submit(lambda lane: None, lambda e: None)
        wedge.set()
    finally:
        pool.close(timeout_s=5)


def test_lane_stall_survivors_keep_serving(model_files):
    """Mid-stream stall through the REAL batcher path: the wedged
    lane's in-flight batch fails with the classified stall (the 503),
    the lane browns out, and later requests succeed on the survivor."""
    hang = threading.Event()
    calls = []
    bst = lgb.Booster(model_file=model_files[0],
                      config=_cfg())

    def predict_fn(rows):
        calls.append(rows.shape)
        if hang.is_set():
            hang.clear()            # wedge exactly one dispatch
            import time
            time.sleep(1.2)
        return bst.predict(rows)

    cfg = _cfg(serve_lanes="2", watchdog_serve_s=0.25,
               serve_batch_deadline_ms=0.0)
    pool = LanePool([None, None], max_inflight=4)
    mb = MicroBatcher(predict_fn, cfg, name="stall", pool=pool)
    try:
        rows = np.random.RandomState(1).randn(2, 6)
        ok = mb.submit(rows)            # healthy warm-up dispatch
        assert np.array_equal(ok, bst.predict(rows))
        hang.set()
        with pytest.raises(StallError):
            mb.submit(rows)             # in-flight on the wedged lane
        assert pool.healthy_count() == 1
        c = TELEMETRY.counters()
        assert c.get("serve_lane_stalls", 0) == 1
        assert c.get("serve_stalls", 0) == 1
        # survivors: the fleet still answers, byte-identically
        for _ in range(3):
            out = mb.submit(rows)
            assert np.array_equal(out, bst.predict(rows))
    finally:
        mb.close(drain=True, timeout_s=10)
        pool.close(timeout_s=5)


# ---------------------------------------------------------------------------
# co-batching
# ---------------------------------------------------------------------------
def test_cobatch_eligibility(model_files, monkeypatch):
    cfg_on = _cfg(serve_cobatch="on")
    bst = lgb.Booster(model_file=model_files[0], config=cfg_on)
    # file-loaded level-descent model with only a device kwarg: fuses
    assert cobatch_key(bst, {"device": True}, cfg_on, True) == \
        ("cobatch", 6)
    # host-walk routing never fuses
    assert cobatch_key(bst, {"device": True}, cfg_on, False) is None
    # custom predict kwargs keep the solo batcher
    assert cobatch_key(bst, {"device": True, "raw_score": True},
                       cfg_on, True) is None
    # off by default
    assert cobatch_key(bst, {"device": True}, _cfg(), True) is None
    # a booster whose device=True routes the in-session binned scan
    # runs a DIFFERENT numeric path than the fused level descent —
    # it must keep its solo batcher (the parity pin)
    monkeypatch.setattr(type(bst), "_can_device_predict",
                        lambda self, n, it, dev: True)
    assert cobatch_key(bst, {"device": True}, cfg_on, True) is None


def test_cobatch_mixed_model_parity_and_amortization(model_files):
    # single lane: the fleet share otherwise splits a 2-request
    # window into per-lane batches (parallelism beats fusion at
    # depth 2) and the fused-dispatch assertion would race it
    pa, pb = model_files
    cfg = _cfg(serve_cobatch="on", serve_batch_deadline_ms=25.0)
    reg = ModelRegistry(cfg)
    try:
        ea = reg.publish("a", pa, predict_kwargs={"device": True})
        eb = reg.publish("b", pb, predict_kwargs={"device": True})
        assert ea.cobatch is not None and ea.cobatch is eb.cobatch
        assert ea.cobatch.names == ["a", "b"]
        rng = np.random.RandomState(11)
        fused = False
        for _attempt in range(5):
            rows_a = rng.randn(2, 6)
            rows_b = rng.randn(3, 6)
            barrier = threading.Barrier(2)
            outs = {}

            def client(name, rows):
                barrier.wait(10)
                _, out = reg.predict(name, rows)
                outs[name] = out

            ta = threading.Thread(target=client, args=("a", rows_a))
            tb = threading.Thread(target=client, args=("b", rows_b))
            ta.start(); tb.start()
            ta.join(60); tb.join(60)
            assert np.array_equal(
                outs["a"], ea.booster.predict(rows_a, device=True))
            assert np.array_equal(
                outs["b"], eb.booster.predict(rows_b, device=True))
            c = TELEMETRY.counters()
            if (c.get("serve_cobatch_fused_models", 0)
                    > c.get("serve_cobatch_dispatches", 0)):
                fused = True            # >= 1 dispatch carried BOTH
                break
        assert fused, "no dispatch ever fused both models"
        # the amortization the fusion exists for: fused dispatches <
        # the per-model dispatches they replaced
        c = TELEMETRY.counters()
        assert c["serve_cobatch_dispatches"] \
            < c["serve_cobatch_fused_models"]
        desc = reg.describe()
        assert desc["a"]["cobatch"]["models"] == ["a", "b"]
        assert desc["b"]["cobatch"]["models"] == ["a", "b"]
    finally:
        reg.close()


def test_cobatch_parity_under_lane_fleet(model_files):
    """Co-batching and the lane fleet composed: mixed-model traffic
    through 2 lanes stays byte-identical per member."""
    pa, pb = model_files
    cfg = _cfg(serve_lanes="2", serve_cobatch="on",
               serve_batch_deadline_ms=10.0)
    reg = ModelRegistry(cfg)
    try:
        ea = reg.publish("a", pa, predict_kwargs={"device": True})
        eb = reg.publish("b", pb, predict_kwargs={"device": True})
        assert ea.cobatch is eb.cobatch is not None
        rng = np.random.RandomState(21)
        jobs = [("a" if i % 2 else "b", rng.randn(1 + i % 3, 6))
                for i in range(10)]
        outs = {}

        def client(i):
            name, rows = jobs[i]
            _, out = reg.predict(name, rows)
            outs[i] = out

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(jobs))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        for i, (name, rows) in enumerate(jobs):
            ref = (ea if name == "a" else eb).booster.predict(
                rows, device=True)
            assert np.array_equal(outs[i], ref), f"job {i} ({name})"
    finally:
        reg.close()


def test_cobatch_group_rebuilds_on_hot_swap(model_files):
    pa, pb = model_files
    cfg = _cfg(serve_cobatch="on")
    reg = ModelRegistry(cfg)
    try:
        ea = reg.publish("a", pa, predict_kwargs={"device": True})
        eb = reg.publish("b", pb, predict_kwargs={"device": True})
        g1 = ea.cobatch
        assert g1 is not None and g1.versions == {"a": 1, "b": 1}
        ea2 = reg.publish("a", pb, predict_kwargs={"device": True})
        g2 = ea2.cobatch
        assert g2 is not None and g2 is not g1
        assert g2.versions == {"a": 2, "b": 1}
        assert reg.get("b").cobatch is g2
        assert g1.batcher.closed      # replaced group drained
        rows = np.random.RandomState(5).randn(4, 6)
        _, out = reg.predict("a", rows)
        assert np.array_equal(out,
                              ea2.booster.predict(rows, device=True))
        # rollback dissolves v2's membership back to v1
        reg.rollback("a")
        e_back = reg.get("a")
        assert e_back.version == 1
        assert e_back.cobatch is not None
        assert e_back.cobatch.versions == {"a": 1, "b": 1}
        _, out = reg.predict("a", rows)
        assert np.array_equal(out,
                              e_back.booster.predict(rows,
                                                     device=True))
    finally:
        reg.close()


def test_cobatch_off_keeps_solo_batchers(model_files):
    pa, pb = model_files
    reg = ModelRegistry(_cfg())          # serve_cobatch defaults off
    try:
        ea = reg.publish("a", pa, predict_kwargs={"device": True})
        eb = reg.publish("b", pb, predict_kwargs={"device": True})
        assert ea.cobatch is None and eb.cobatch is None
        assert ea.cobatch_k is None
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# binary wire format
# ---------------------------------------------------------------------------
def test_parse_binary_rows_roundtrip_and_errors():
    rows = np.random.RandomState(2).randn(5, 6).astype("<f4")
    got = parse_binary_rows(rows.tobytes(), 6)
    assert got.shape == (5, 6)
    assert np.array_equal(got, rows)
    with pytest.raises(ValueError, match="multiple"):
        parse_binary_rows(rows.tobytes()[:-3], 6)
    with pytest.raises(ValueError, match="empty"):
        parse_binary_rows(b"", 6)


def test_http_binary_request_and_response_parity(model_files):
    cfg = _cfg(serve_lanes="2")
    reg = ModelRegistry(cfg)
    fe = ServingFrontend(reg, cfg)
    try:
        entry = reg.publish("m", model_files[0],
                            predict_kwargs={"device": True})
        port = fe.start(0).server_address[1]
        rows32 = np.random.RandomState(8).randn(6, 6).astype("<f4")
        ref = entry.booster.predict(
            rows32.astype(np.float64), device=True)

        # binary in, JSON out
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict/m",
            data=rows32.tobytes(),
            headers={"Content-Type": BINARY_F32})
        body = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert np.array_equal(np.asarray(body["predictions"]), ref)

        # binary in, binary out: raw little-endian f64, exact
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict/m",
            data=rows32.tobytes(),
            headers={"Content-Type": BINARY_F32,
                     "Accept": BINARY_F64})
        resp = urllib.request.urlopen(req, timeout=60)
        assert resp.headers.get("Content-Type") == BINARY_F64
        assert resp.headers.get("X-Model-Version") == "1"
        assert resp.headers.get("X-Prediction-Shape") == "6"
        got = np.frombuffer(resp.read(), dtype="<f8")
        assert np.array_equal(got, ref)
        assert TELEMETRY.counters().get("serve_binary_requests",
                                        0) == 2

        # malformed frame: 400 for the one bad client, no batch harm
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict/m",
            data=rows32.tobytes()[:-2],
            headers={"Content-Type": BINARY_F32})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 400
        # JSON clients still fine afterwards
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict/m",
            data=json.dumps(
                {"rows": rows32.astype(float).tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        body = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert np.array_equal(np.asarray(body["predictions"]), ref)
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------
def test_models_endpoint_reports_fleet_state(model_files):
    cfg = _cfg(serve_lanes="2")
    reg = ModelRegistry(cfg)
    fe = ServingFrontend(reg, cfg)
    try:
        reg.publish("m", model_files[0],
                    predict_kwargs={"device": True})
        port = fe.start(0).server_address[1]
        reg.predict("m", np.random.RandomState(0).randn(2, 6))
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/models", timeout=30).read())
        fleet = body["_fleet"]
        assert fleet["n_lanes"] == 2
        assert fleet["healthy_lanes"] == 2
        assert [ln["lane"] for ln in fleet["lanes"]] == [0, 1]
        for ln in fleet["lanes"]:
            assert set(ln) == {"lane", "device", "queue_depth",
                               "dispatches", "stalls", "stalled"}
        assert sum(ln["dispatches"] for ln in fleet["lanes"]) >= 1
    finally:
        fe.stop()


def test_no_fleet_block_without_pool(model_files):
    reg = ModelRegistry(_cfg())
    try:
        reg.publish("m", model_files[0])
        assert "_fleet" not in reg.describe()
    finally:
        reg.close()


def test_warm_predictor_devices_param(model_files):
    import jax
    bst = lgb.Booster(model_file=model_files[0], config=_cfg())
    dev = jax.local_devices()[0]
    bst.warm_predictor((1, 8), devices=(dev,))
    rows = np.random.RandomState(6).randn(3, 6)
    with jax.default_device(dev):
        out = bst.predict(rows, device=True)
    assert np.array_equal(out, bst.predict(rows, device=True))
