"""R-package smoke: builds the .Call shim with R CMD SHLIB and runs the
demo (skipped when R is not installed, as in the CI image; the shim's
C++ is still syntax-checked against stub headers here)."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_r_shim_syntax():
    """The .Call shim must stay compilable: syntax-only g++ pass
    against minimal stub R headers."""
    stub = os.path.join(REPO, "tests", "_rstub")
    os.makedirs(stub, exist_ok=True)
    with open(os.path.join(stub, "R.h"), "w") as f:
        f.write("#pragma once\n")
    with open(os.path.join(stub, "Rinternals.h"), "w") as f:
        f.write(
            "#pragma once\n#include <cstddef>\n"
            "typedef struct SEXPREC* SEXP;\n"
            "extern \"C\" {\nextern SEXP R_NilValue;\n"
            "SEXP R_MakeExternalPtr(void*, SEXP, SEXP);\n"
            "void* R_ExternalPtrAddr(SEXP);\n"
            "void R_ClearExternalPtr(SEXP);\n"
            "void Rf_error(const char*, ...);\n"
            "int Rf_asInteger(SEXP);\nSEXP Rf_asChar(SEXP);\n"
            "const char* CHAR(SEXP);\nint Rf_length(SEXP);\n"
            "double* REAL(SEXP);\nSEXP Rf_allocVector(unsigned, long);\n"
            "SEXP Rf_ScalarInteger(int);\n}\n"
            "#define PROTECT(x) (x)\n#define UNPROTECT(n) ((void)(n))\n"
            "#define REALSXP 14\n")
    r = subprocess.run(
        ["g++", "-fsyntax-only", f"-I{stub}",
         os.path.join(REPO, "R-package", "src", "lightgbm_R.cpp")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="R not installed")
def test_r_demo_trains_and_predicts():
    src = os.path.join(REPO, "R-package", "src")
    r = subprocess.run(
        ["R", "CMD", "SHLIB", "lightgbm_R.cpp",
         "-L../../lightgbm_tpu/native", "-llgbm_tpu",
         f"-Wl,-rpath,{os.path.join(REPO, 'lightgbm_tpu', 'native')}"],
        cwd=src, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["Rscript", "R-package/demo/binary.R"], cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "roundtrip ok" in r.stdout
