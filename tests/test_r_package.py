"""R-package binding tests.

The CI image has no R, so the .Call shim (R-package/src/lightgbm_R.cpp)
is EXECUTED for real against a stub libR (R-package/src/rstub — the
subset of R's C API the shim touches) by a plain C host
(tests/r_host_driver.c) linking the actual liblgbm_tpu.so: dataset from
a column-major matrix, training, prediction, model save/reload parity.
Where a real R exists the same shim builds against the real headers and
the demo script runs end-to-end (test_r_demo_trains_and_predicts,
skipless there).  Reference: R-package/src/lightgbm_R.cpp + R tests.
"""
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "lightgbm_tpu", "native")
LIB = os.path.join(NATIVE, "liblgbm_tpu.so")
RSRC = os.path.join(REPO, "R-package", "src")
RSTUB = os.path.join(RSRC, "rstub")



@pytest.mark.slow
def test_r_shim_executes_via_stub_host(native_lib, tmp_path):
    """Every line of the .Call shim runs for real: stub-libR host
    drives train -> predict -> save -> reload -> parity over the
    actual C ABI."""
    exe = str(tmp_path / "r_host")
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17",
         "-I", RSTUB,
         os.path.join(RSRC, "lightgbm_R.cpp"),
         os.path.join(RSTUB, "rstub.c"),
         os.path.join(REPO, "tests", "r_host_driver.c"),
         "-o", exe, "-L", NATIVE, "-llgbm_tpu", "-lm",
         f"-Wl,-rpath,{NATIVE}"],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    run = subprocess.run([exe, str(tmp_path / "model.txt")],
                         capture_output=True, text=True, env=env,
                         timeout=600)
    assert run.returncode == 0, \
        f"stdout={run.stdout}\nstderr={run.stderr}"
    assert "R-HOST OK" in run.stdout


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="R not installed")
def test_r_demo_trains_and_predicts():
    r = subprocess.run(
        ["R", "CMD", "SHLIB", "lightgbm_R.cpp",
         "-L../../lightgbm_tpu/native", "-llgbm_tpu",
         f"-Wl,-rpath,{os.path.join(REPO, 'lightgbm_tpu', 'native')}"],
        cwd=RSRC, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(["Rscript", "R-package/demo/binary.R"], cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "roundtrip ok" in r.stdout


def test_r_sources_structurally_sound():
    """No R interpreter exists in this image, so the R sources get a
    string/comment-aware bracket-balance scan — catching truncated
    edits and mismatched blocks that would stop `source()` cold."""
    import glob

    files = sorted(glob.glob(os.path.join(REPO, "R-package", "R",
                                          "*.R")))
    files.append(os.path.join(REPO, "R-package", "demo", "binary.R"))
    assert len(files) >= 8     # the round-5 surface breadth
    for p in files:
        code_chars = []
        for ln in open(p):
            i, n, in_s = 0, len(ln), None
            while i < n:
                ch = ln[i]
                if in_s:
                    if ch == "\\":
                        i += 2
                        continue
                    if ch == in_s:
                        in_s = None
                    i += 1
                    continue
                if ch in "\"'`":
                    in_s = ch
                    i += 1
                    continue
                if ch == "#":
                    break
                code_chars.append(ch)
                i += 1
            code_chars.append("\n")
        code = "".join(code_chars)
        pair = {")": "(", "}": "{", "]": "["}
        depth = {"(": 0, "{": 0, "[": 0}
        for ch in code:
            if ch in depth:
                depth[ch] += 1
            elif ch in pair:
                depth[pair[ch]] -= 1
                assert depth[pair[ch]] >= 0, f"extra {ch} in {p}"
        assert all(v == 0 for v in depth.values()), (p, depth)
