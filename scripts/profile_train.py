"""Profile a training chunk on top of the runtime telemetry subsystem.

Round 9 rewrite: this used to be a standalone one-off with private
timers; it now drives the SAME instrumentation a production run uses
(``telemetry=trace`` — docs/OBSERVABILITY.md):

1. trains a warm-up + a measured chunk under telemetry trace mode
   (host spans, device fence, named-scope phase annotation),
2. exports the telemetry Perfetto file + newline-JSON events
   (load the ``.perfetto.json`` in ui.perfetto.dev),
3. prints the counter snapshot (host-dispatch vs device-wait per
   tree — the ROOFLINE headroom #3 split), and
4. when a jax profiler xplane is available, aggregates device-op time
   by telemetry phase (the ``tel.histogram`` / ``tel.split_finder`` /
   ... named scopes the trace mode stamps into the HLO metadata) plus
   the top ops, as before.

Usage: python scripts/profile_train.py [rows] [iters] [out_prefix]
  out_prefix default: /tmp/lgbtpu_profile/telemetry
  env: BENCH_PARAMS='{...}' param overrides (as in bench.py)
"""
import glob
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np


def device_op_table(tdir):
    """Aggregate device-plane op durations from the newest xplane in
    ``tdir``, grouped by telemetry phase (named-scope prefix ``tel.``)
    and by op name.  Returns (phase_ms, op_ms, op_calls, total_ms) or
    None when no device plane exists (CPU seam without an xplane)."""
    import jax

    pbs = sorted(glob.glob(os.path.join(
        tdir, "**", "*.xplane.pb"), recursive=True))
    if not pbs:
        return None
    if not hasattr(jax.profiler, "ProfileData"):
        # this jaxlib cannot parse xplanes in-process; the serialized
        # trace is still on disk for TensorBoard/xprof
        print(f"(xplane written to {pbs[-1]}; this jax has no "
              "ProfileData parser — open it in xprof/TensorBoard)",
              file=sys.stderr)
        return None
    data = jax.profiler.ProfileData.from_serialized_xspace(
        open(pbs[-1], "rb").read())
    phase = defaultdict(float)
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for plane in data.planes:
        if "TPU" not in plane.name and "/device" not in plane.name:
            continue
        for line in plane.lines:
            if "Ops" not in line.name:
                continue
            for ev in line.events:
                dur = ev.duration_ns / 1e6
                agg[ev.name] += dur
                cnt[ev.name] += 1
                total += dur
                # telemetry trace mode stamps jax.named_scope("tel.X")
                # into op metadata; xplane op names carry the scope
                # path, so a substring match attributes the op
                name = ev.name
                tag = "(unattributed)"
                if "tel." in name:
                    # scope path "…/tel.<phase>/…" -> "tel.<phase>"
                    tag = "tel." + name.split("tel.", 1)[1].split(
                        "/", 1)[0]
                phase[tag] += dur
    if not agg:
        return None
    return phase, agg, cnt, total


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    out = (sys.argv[3] if len(sys.argv) > 3
           else "/tmp/lgbtpu_profile/telemetry")
    os.environ.setdefault("BENCH_ROWS", str(rows))
    import jax

    import bench
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.telemetry import TELEMETRY

    # trace mode BEFORE the first compile: the named-scope phase
    # annotation is stamped at trace time
    TELEMETRY.configure("trace", out=out)

    X, y, w = bench.make_data(rows, bench.BENCH_FEATURES)
    params = {
        "objective": "binary", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
        "hist_compute_dtype": "bfloat16", "quantized_grad": True,
    }
    extra = os.environ.get("BENCH_PARAMS")
    if extra:
        import json
        params.update(json.loads(extra))
    cfg = Config.from_params(params)
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = GBDT(cfg, core)
    span = TELEMETRY.start_span("profile_warm")
    g.train_chunk(iters)          # compile + warm
    np.asarray(g.scores[:, :8])
    TELEMETRY.end_span(span)

    tdir = "/tmp/lgbtpu_profile"
    import shutil
    shutil.rmtree(os.path.join(tdir, "plugins"), ignore_errors=True)
    span = TELEMETRY.start_span("profile_measure")
    try:
        with jax.profiler.trace(tdir):
            g.train_chunk(iters)
            np.asarray(g.scores[:, :8])
        profiled = True
    except Exception as e:  # profiler availability is env-dependent
        print(f"jax profiler unavailable ({type(e).__name__}: {e}); "
              "telemetry-only run", file=sys.stderr)
        g.train_chunk(iters)
        np.asarray(g.scores[:, :8])
        profiled = False
    TELEMETRY.end_span(span)

    snap = TELEMETRY.snapshot()
    paths = TELEMETRY.export(out)
    print(f"telemetry: {paths[0]}")
    print(f"perfetto:  {paths[1]}  (load in ui.perfetto.dev)")
    d = snap.get("derived", {})
    print(f"\n== host wall over {2 * iters} trees "
          f"({rows // 1000}k rows) ==")
    print(f"host_dispatch {d.get('host_dispatch_ms_per_tree', 0):.3f} "
          f"ms/tree, device_wait "
          f"{d.get('device_wait_ms_per_tree', 0):.3f} ms/tree")
    for k in sorted(snap["counters"]):
        if k.startswith("phase_"):
            print(f"  {k} = {snap['counters'][k]:.1f}")

    table = device_op_table(tdir) if profiled else None
    if table is None:
        print("\n(no device xplane — per-op attribution needs a chip "
              "or a profiler-enabled backend; telemetry spans above "
              "are the host-side record)")
        return
    phase, agg, cnt, total = table
    print(f"\n== device time by telemetry phase ==")
    for tag, ms in sorted(phase.items(), key=lambda kv: -kv[1]):
        print(f"{ms / iters:9.3f} ms/tree {100 * ms / total:5.1f}%  "
              f"{tag}")
    print(f"\n== device op time over {iters} trees ==")
    print(f"{'ms/tree':>9} {'pct':>6} {'calls':>7}  op")
    for name, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:25]:
        print(f"{ms / iters:9.3f} {100 * ms / total:5.1f}% "
              f"{cnt[name]:7d}  {name[:90]}")
    print(f"{total / iters:9.3f} 100.0%          TOTAL device")


if __name__ == "__main__":
    main()
