"""Profile the 1M-row training chunk on the real chip and print the
per-op device-time breakdown (jax.profiler xplane parsed with
jax.profiler.ProfileData — no TensorBoard needed).

Usage: python scripts/profile_train.py [rows] [iters]
"""
import glob
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    os.environ.setdefault("BENCH_ROWS", str(rows))
    import jax

    import bench
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    X, y, w = bench.make_data(rows, bench.BENCH_FEATURES)
    params = {
        "objective": "binary", "num_leaves": 255, "max_bin": 63,
        "learning_rate": 0.1, "verbose": -1, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0,
        "hist_compute_dtype": "bfloat16", "quantized_grad": True,
    }
    extra = os.environ.get("BENCH_PARAMS")
    if extra:
        import json
        params.update(json.loads(extra))
    cfg = Config.from_params(params)
    core = lgb.Dataset(X, label=y).construct(cfg)
    g = GBDT(cfg, core)
    g.train_chunk(iters)          # compile + warm
    np.asarray(g.scores[:, :8])

    tdir = "/tmp/lgbtpu_profile"
    import shutil
    shutil.rmtree(tdir, ignore_errors=True)
    with jax.profiler.trace(tdir):
        g.train_chunk(iters)
        np.asarray(g.scores[:, :8])

    # aggregate device-plane event durations by op name
    pb = sorted(glob.glob(os.path.join(
        tdir, "**", "*.xplane.pb"), recursive=True))[-1]
    data = jax.profiler.ProfileData.from_serialized_xspace(
        open(pb, "rb").read())
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for plane in data.planes:
        if "TPU" not in plane.name and "/device" not in plane.name:
            continue
        for line in plane.lines:
            if "XLA Ops" not in line.name and "Ops" not in line.name:
                continue
            for ev in line.events:
                dur = ev.duration_ns / 1e6
                agg[ev.name] += dur
                cnt[ev.name] += 1
                total += dur
    print(f"\n== device op time over {iters} trees "
          f"({rows//1000}k rows) ==")
    print(f"{'ms/tree':>9} {'pct':>6} {'calls':>7}  op")
    for name, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:25]:
        print(f"{ms/iters:9.3f} {100*ms/total:5.1f}% {cnt[name]:7d}  "
              f"{name[:90]}")
    print(f"{total/iters:9.3f} 100.0%          TOTAL device")


if __name__ == "__main__":
    main()
