#!/usr/bin/env python
"""Telemetry coverage lint: every span/phase name in the code must be
documented in docs/OBSERVABILITY.md, and vice versa.

Thin wrapper over analysis rule ``TEL001``
(lightgbm_tpu/analysis/teldoc_rule.py) — the check logic was re-homed
into the `python -m lightgbm_tpu.analysis` engine in the
static-analysis round; this entry point keeps the historical CLI
contract (rc 0 clean, rc 1 drift, findings on stderr) for tooling that
calls it directly.  ``scripts/bench_smoke.sh`` now runs the full
analysis suite instead.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    from lightgbm_tpu.analysis import run_rules, unsuppressed
    findings = run_rules(["TEL001"], check_suppressions=False)
    live = unsuppressed(findings)
    for f in live:
        print(f"DRIFT: {f.message}", file=sys.stderr)
    if live:
        print(f"check_telemetry_coverage: {len(live)} drift error(s)",
              file=sys.stderr)
        return 1
    print("check_telemetry_coverage: span/phase names consistent with "
          "docs/OBSERVABILITY.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
