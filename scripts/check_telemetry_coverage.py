#!/usr/bin/env python
"""Telemetry coverage lint: every span/phase name in the code must be
documented in docs/OBSERVABILITY.md, and vice versa.

The span map is the contract between the instrumentation and anyone
reading a Perfetto trace — an undocumented span is a mystery slice in
the UI, and a documented-but-deleted span means the doc (and any
dashboard built on it) silently rotted.  Same discipline as
scripts/check_carry_layout.py: fail the smoke before spending a
training run.

Scans ``lightgbm_tpu/**/*.py``, ``scripts/profile_train.py`` and
``bench.py`` for

    .span("name"...)   .start_span("name"...)   .phase("name"...)

(string-literal first arguments only — dynamic names are a lint error
by construction: they cannot be in the glossary) and compares the set
against the first-column backticked names of the "Span map" and
"Trace-mode phase annotations" tables in docs/OBSERVABILITY.md.

Usage: python scripts/check_telemetry_coverage.py  (rc 0 clean, rc 1 drift)
"""
import glob
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CALL_RE = re.compile(
    r"\.(?:span|start_span|phase)\(\s*(?:f?)([\"'])([^\"']+)\1")
DYNAMIC_RE = re.compile(r"\.(?:span|start_span|phase)\(\s*[^\"')]")
# telemetry.py itself defines the API (its internal span("device_wait")
# helper IS a real span and is scanned too)
SOURCES = (
    sorted(glob.glob(os.path.join(REPO, "lightgbm_tpu", "**", "*.py"),
                     recursive=True))
    + [os.path.join(REPO, "scripts", "profile_train.py"),
       os.path.join(REPO, "bench.py")]
)
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

ERRORS = []


def err(msg):
    ERRORS.append(msg)
    print(f"DRIFT: {msg}", file=sys.stderr)


def code_spans():
    names = {}
    for path in SOURCES:
        with open(path) as f:
            src = f.read()
        rel = os.path.relpath(path, REPO)
        for m in CALL_RE.finditer(src):
            names.setdefault(m.group(2), set()).add(rel)
        for m in DYNAMIC_RE.finditer(src):
            frag = src[m.start():m.start() + 60].splitlines()[0]
            # allow the API definition sites in telemetry.py and
            # variable-forwarding helpers that pass a `name` parameter
            if "telemetry.py" in rel or re.match(
                    r"\.(?:span|start_span|phase)\(\s*(?:self|name|f?\")",
                    frag):
                continue
            err(f"{rel}: dynamic span/phase name cannot be linted "
                f"against the glossary: {frag!r}")
    return names


def doc_spans():
    with open(DOC) as f:
        text = f.read()
    names = set()
    in_table = False
    for line in text.splitlines():
        if line.startswith("| Span |") or line.startswith("| Phase |"):
            in_table = True
            continue
        if in_table:
            m = re.match(r"\|\s*`([^`]+)`\s*\|", line)
            if m:
                names.add(m.group(1))
            elif not line.startswith("|"):
                in_table = False
    return names


def main():
    code = code_spans()
    doc = doc_spans()
    if not doc:
        err("no span map tables parsed from docs/OBSERVABILITY.md")
    for name, sites in sorted(code.items()):
        if name not in doc:
            err(f"span {name!r} (used in {', '.join(sorted(sites))}) "
                "is missing from the docs/OBSERVABILITY.md span map")
    for name in sorted(doc - set(code)):
        err(f"docs/OBSERVABILITY.md documents span {name!r} but no "
            "span(/phase( call with that name exists in the code")
    if ERRORS:
        print(f"check_telemetry_coverage: {len(ERRORS)} drift error(s)",
              file=sys.stderr)
        return 1
    print(f"check_telemetry_coverage: {len(code)} span/phase names "
          "consistent with docs/OBSERVABILITY.md")
    return 0


if __name__ == "__main__":
    sys.exit(main())
