#!/bin/sh
# Fast bench.py plumbing check: tiny shapes, two iterations per scale,
# no local-reference anchors, no f32 rerun.  Catches import/flag/JSON
# regressions in the bench driver (the r5 bench shipped with a path
# that could only fail under the perf driver, rc=124) from the test
# suite instead — tests/test_bench_smoke.py runs this under the `slow`
# marker and asserts the one-line JSON contract.
#
# Runs on whatever backend JAX selects (CPU included); the point is
# plumbing, not performance.
set -e
cd "$(dirname "$0")/.."
# static-analysis suite first: the compiled-program invariant rules
# (HLO001-HLO008), the trace-safety AST pass, the Config contract and
# the re-homed carry-layout/telemetry-glossary lints all run as one
# engine (docs/STATIC_ANALYSIS.md).  Any unsuppressed finding fails
# the smoke before a training run is spent on it.  (JSON to stderr —
# bench stdout is ONE JSON line by contract.)
python -m lightgbm_tpu.analysis --json >&2
# profile_train smoke (round 9: rewritten on the telemetry spans):
# tiny shape, asserts the Perfetto + JSONL files actually get written
# (stdout redirected — the bench stdout contract is ONE JSON line)
BENCH_PARAMS='{"num_leaves":15,"max_bin":31}' \
python scripts/profile_train.py 2048 2 /tmp/lgbtpu_smoke/telemetry >&2
test -s /tmp/lgbtpu_smoke/telemetry.perfetto.json
test -s /tmp/lgbtpu_smoke/telemetry.jsonl
# construct pipeline + binary-cache v2 plumbing (round 11): build a
# tiny dataset through the parallel pipeline, save the v2 cache,
# reload it (memmap path) and assert byte equality — catches cache
# format regressions before the bench's construct block reports them
python - >&2 <<'EOF'
import os, tempfile
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset_io import load_binary, save_binary
rng = np.random.RandomState(0)
X = rng.randn(512, 6)
X[rng.rand(512, 6) < 0.2] = 0.0
core = lgb.Dataset(X, label=(X[:, 0] > 0)).construct(
    Config.from_params({"verbose": -1, "max_bin": 31}))
p = os.path.join(tempfile.mkdtemp(prefix="lgbtpu_smoke_"), "t.bin")
save_binary(core, p)
assert np.array_equal(np.asarray(load_binary(p).group_bins),
                      np.asarray(core.group_bins))
print("construct cache-v2 smoke ok")
EOF
# reliability probe (round 12): checkpoint save overhead + one smoke
# fault-plan recovery — a child run SIGKILLed mid-train through the
# fault harness, auto-resumed, asserted byte-identical vs the cold
# run; writes /tmp/lgbtpu_smoke/reliability.json for test_bench_smoke
python scripts/reliability_probe.py /tmp/lgbtpu_smoke/reliability.json >&2
test -s /tmp/lgbtpu_smoke/reliability.json
BENCH_ROWS=${BENCH_ROWS:-4096} \
BENCH_ITERS=${BENCH_ITERS:-2} \
BENCH_VALID_ROWS=${BENCH_VALID_ROWS:-2048} \
BENCH_LEAVES=${BENCH_LEAVES:-31} \
BENCH_BIG=0 \
BENCH_LTR_QUERIES=${BENCH_LTR_QUERIES:-40} \
BENCH_LTR_ITERS=${BENCH_LTR_ITERS:-2} \
BENCH_PREDICT_TRAIN_ROWS=${BENCH_PREDICT_TRAIN_ROWS:-2048} \
BENCH_PREDICT_ITERS=${BENCH_PREDICT_ITERS:-3} \
BENCH_PREDICT_ROWS=${BENCH_PREDICT_ROWS:-4096} \
BENCH_PREDICT_CALLS=${BENCH_PREDICT_CALLS:-10} \
BENCH_LOCAL_REF=0 \
BENCH_SKIP_F32=1 \
BENCH_BUDGET_S=${BENCH_BUDGET_S:-600} \
exec python bench.py
