#!/bin/sh
# Fast bench.py plumbing check: tiny shapes, two iterations per scale,
# no local-reference anchors, no f32 rerun.  Catches import/flag/JSON
# regressions in the bench driver (the r5 bench shipped with a path
# that could only fail under the perf driver, rc=124) from the test
# suite instead — tests/test_bench_smoke.py runs this under the `slow`
# marker and asserts the one-line JSON contract.
#
# Runs on whatever backend JAX selects (CPU included); the point is
# plumbing, not performance.
set -e
cd "$(dirname "$0")/.."
# static-analysis suite first: the compiled-program invariant rules
# (HLO001-HLO008), the trace-safety AST pass, the Config contract and
# the re-homed carry-layout/telemetry-glossary lints all run as one
# engine (docs/STATIC_ANALYSIS.md).  Any unsuppressed finding fails
# the smoke before a training run is spent on it.  (JSON to stderr —
# bench stdout is ONE JSON line by contract.)
python -m lightgbm_tpu.analysis --json >&2
# profile_train smoke (round 9: rewritten on the telemetry spans):
# tiny shape, asserts the Perfetto + JSONL files actually get written
# (stdout redirected — the bench stdout contract is ONE JSON line)
BENCH_PARAMS='{"num_leaves":15,"max_bin":31}' \
python scripts/profile_train.py 2048 2 /tmp/lgbtpu_smoke/telemetry >&2
test -s /tmp/lgbtpu_smoke/telemetry.perfetto.json
test -s /tmp/lgbtpu_smoke/telemetry.jsonl
# construct pipeline + binary-cache v2 plumbing (round 11): build a
# tiny dataset through the parallel pipeline, save the v2 cache,
# reload it (memmap path) and assert byte equality — catches cache
# format regressions before the bench's construct block reports them
python - >&2 <<'EOF'
import os, tempfile
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset_io import load_binary, save_binary
rng = np.random.RandomState(0)
X = rng.randn(512, 6)
X[rng.rand(512, 6) < 0.2] = 0.0
core = lgb.Dataset(X, label=(X[:, 0] > 0)).construct(
    Config.from_params({"verbose": -1, "max_bin": 31}))
p = os.path.join(tempfile.mkdtemp(prefix="lgbtpu_smoke_"), "t.bin")
save_binary(core, p)
assert np.array_equal(np.asarray(load_binary(p).group_bins),
                      np.asarray(core.group_bins))
print("construct cache-v2 smoke ok")
EOF
# seam-coverage lint (round 19, TEL001-style two-way): every fault
# seam registered in reliability/faults.py must be exercised by at
# least one test/probe AND documented in docs/RELIABILITY.md, and the
# doc must not carry stale seams — fails loudly when a new seam lands
# untested
python scripts/check_seam_coverage.py >&2
# reliability probe (round 12): checkpoint save overhead + one smoke
# fault-plan recovery — a child run SIGKILLed mid-train through the
# fault harness, auto-resumed, asserted byte-identical vs the cold
# run; writes /tmp/lgbtpu_smoke/reliability.json for test_bench_smoke
python scripts/reliability_probe.py /tmp/lgbtpu_smoke/reliability.json >&2
test -s /tmp/lgbtpu_smoke/reliability.json
# chaos probe (round 19): a fixed budget of SEEDED multi-fault plans
# across train/serve/continuous — kills, OOMs, transient errors, and
# the hang/slow stall shapes bounded by the deadline watchdog — every
# plan gated by the invariant registry (byte-identical resume, no
# partial artifacts, ledger convergence, serving parity, loud
# failure) and replayable from its printed seed.  CHAOS_SEEDS /
# CHAOS_BUDGET_S widen the sweep for a nightly job without touching
# the tier-1 wall; asserted by test_bench_smoke on the JSON
python scripts/chaos_probe.py /tmp/lgbtpu_smoke/chaos.json >&2
test -s /tmp/lgbtpu_smoke/chaos.json
# distributed-observability probe (round 13): serving latency
# histograms exported as a Prometheus textfile, plus a crash
# flight-recorder smoke — one fault injected through the plan
# grammar, the dump must exist and name the seam
rm -f /tmp/lgbtpu_smoke/flight*.flight.json
python - >&2 <<'EOF'
import glob, json
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import TELEMETRY
from lightgbm_tpu.reliability.faults import FAULTS
TELEMETRY.configure("counters")
TELEMETRY.flight.arm("/tmp/lgbtpu_smoke/flight")
rng = np.random.RandomState(0)
X = rng.randn(400, 5)
bst = lgb.train({"objective": "regression", "verbose": -1,
                 "num_leaves": 7, "min_data_in_leaf": 5},
                lgb.Dataset(X, label=X[:, 0]), 3, verbose_eval=False)
for n in (1, 3, 16, 40):
    bst.predict(X[:n], device=True)
TELEMETRY.write_prom("/tmp/lgbtpu_smoke/metrics.prom")
FAULTS.configure("predict.dispatch:1:RuntimeError")
try:
    bst.predict(X[:4], device=True)
    raise SystemExit("fault plan did not fire")
except RuntimeError:
    pass
FAULTS.reset()
dumps = glob.glob("/tmp/lgbtpu_smoke/flight*.flight.json")
assert dumps, "flight recorder wrote no dump"
d = json.load(open(dumps[-1]))
assert d["seam"] == "predict.dispatch", d["seam"]
assert d["events"], "flight dump carries no events"
print(f"observability smoke ok: prom + flight dump ({d['reason']})")
EOF
test -s /tmp/lgbtpu_smoke/metrics.prom
# scrape-parse the textfile with a ten-line stdlib parser: histogram
# buckets must be cumulative (monotone) and end at +Inf == _count
python - >&2 <<'EOF'
hists = {}
for ln in open("/tmp/lgbtpu_smoke/metrics.prom"):
    if ln.startswith("#") or not ln.strip():
        continue
    name, val = ln.rsplit(None, 1)
    if "_bucket{le=" in name:
        base, le = name.split("_bucket{le=\"", 1)
        hists.setdefault(base, []).append((le[:-2], float(val)))
assert "ltpu_predict_latency_ms" in hists, sorted(hists)
for base, buckets in hists.items():
    vals = [v for _, v in buckets]
    assert vals == sorted(vals), f"{base} buckets not cumulative"
    assert buckets[-1][0] == "+Inf", f"{base} missing +Inf bucket"
print(f"prom scrape ok: {len(hists)} histogram series, "
      f"buckets monotone")
EOF
# distributed-tracing probe (round 23): a real HTTP request carrying
# an X-Ltpu-Trace header through the serving stack in spans mode —
# header echoed back, the merged Perfetto timeline flow-links the
# request span to its coalesced dispatch span, and an injected
# dispatch stall journals its seam WITH the request's trace id;
# asserted by test_bench_smoke on the JSON it writes
python scripts/trace_probe.py /tmp/lgbtpu_smoke/trace.json >&2
test -s /tmp/lgbtpu_smoke/trace.json
# continuous-training probe (round 15): 2-cycle in-process loop
# (ingest -> append-construct -> continue-train -> gated publish),
# served-vs-direct parity, a forced live regression -> auto-rollback,
# and a continuous.cycle SIGKILL fault-plan smoke proving the cycle
# state machine resumes to a byte-identical published model; asserted
# by test_bench_smoke on the JSON it writes
python scripts/continuous_probe.py /tmp/lgbtpu_smoke/continuous.json >&2
test -s /tmp/lgbtpu_smoke/continuous.json
# model-quality observability probe (round 17): train with quality=on
# (profile sidecar persisted), serve sampled traffic through a real
# registry with drift monitors armed — byte parity + zero drift on
# in-distribution rows, a deliberately shifted stream blowing a
# per-feature PSI past threshold with the warn fired, ltpu_quality_*
# gauges present in the Prometheus text, and the operator report CLI
# agreeing (rc 1 + the drifted feature named); asserted by
# test_bench_smoke on the JSON it writes
python scripts/quality_probe.py /tmp/lgbtpu_smoke/quality.json >&2
test -s /tmp/lgbtpu_smoke/quality.json
# serving probe (round 14): in-process registry + micro-batching
# frontend under concurrent single-row clients through real HTTP —
# parity vs direct predict, coalescing actually occurring
# (dispatches < requests), a generous p99 bound and clean queue
# drain on shutdown are asserted by test_bench_smoke on the JSON.
# Round 20 adds the fleet probes to the same JSON: lane_scaling (the
# SAME closed-loop load on 1 then 2 simulated lanes over a per-row
# simulated device wall, gated at 2-lane rows/s >= 1.5x single-lane)
# and mixed_model (3 co-batched models under open-loop traffic,
# fused dispatches strictly fewer than the per-model dispatches
# they replaced, per-member parity)
SERVE_CLIENTS=${SERVE_CLIENTS:-8} \
SERVE_REQUESTS=${SERVE_REQUESTS:-12} \
SERVE_LANE_PROBE=${SERVE_LANE_PROBE:-1} \
SERVE_LANE_N=${SERVE_LANE_N:-2} \
SERVE_MIXED_PROBE=${SERVE_MIXED_PROBE:-1} \
python scripts/serve_bench.py /tmp/lgbtpu_smoke/serve.json >&2
test -s /tmp/lgbtpu_smoke/serve.json
# BENCH_SHARD pins the round-16 shard_construct probe on: 2 simulated
# participants, merged-mapper + bin parity vs the single-matrix route,
# shard-cache v2 manifest round trip — its JSON block is asserted by
# tests/test_bench_smoke.py
# BENCH_COMPACT pins the round-18 compact_bins probe on: 8bit vs
# 4bit construct rows/s on the same max_bin=15 draw, host + device
# bin-matrix bytes with the >=2x packing-ratio gate, and the
# byte-identical-trees parity gate — its JSON block is asserted by
# tests/test_bench_smoke.py
# BENCH_DIST pins the distributed_exchange probe on: the r21
# hist_exchange codec over the REAL 2-process TCP transport, wire
# bytes per mode with the q16 >=2x / q8 >=4x payload gates and
# host-codec bit-exactness — its JSON block is asserted by
# tests/test_bench_smoke.py
BENCH_ROWS=${BENCH_ROWS:-4096} \
BENCH_ITERS=${BENCH_ITERS:-2} \
BENCH_VALID_ROWS=${BENCH_VALID_ROWS:-2048} \
BENCH_LEAVES=${BENCH_LEAVES:-31} \
BENCH_BIG=0 \
BENCH_LTR_QUERIES=${BENCH_LTR_QUERIES:-40} \
BENCH_LTR_ITERS=${BENCH_LTR_ITERS:-2} \
BENCH_PREDICT_TRAIN_ROWS=${BENCH_PREDICT_TRAIN_ROWS:-2048} \
BENCH_PREDICT_ITERS=${BENCH_PREDICT_ITERS:-3} \
BENCH_PREDICT_ROWS=${BENCH_PREDICT_ROWS:-4096} \
BENCH_PREDICT_CALLS=${BENCH_PREDICT_CALLS:-10} \
BENCH_LOCAL_REF=0 \
BENCH_SKIP_F32=1 \
BENCH_SHARD=1 \
BENCH_SHARD_PARTICIPANTS=${BENCH_SHARD_PARTICIPANTS:-2} \
BENCH_COMPACT=1 \
BENCH_DIST=1 \
BENCH_DIST_REPS=${BENCH_DIST_REPS:-2} \
BENCH_BUDGET_S=${BENCH_BUDGET_S:-600} \
exec python bench.py
