"""Model-quality observability probe: train → serve sampled traffic →
assert zero drift on in-distribution rows, nonzero PSI on a
deliberately shifted stream, Prometheus gauges present.

Run by ``scripts/bench_smoke.sh`` and asserted by
``tests/test_bench_smoke.py``.  One in-process pass:

1. Train a small model with ``quality=on`` — the QualityProfile is
   captured at train end and persisted as ``<model>.quality.json``.
2. Publish the model file into a real ModelRegistry with
   ``quality_sample_rate=1`` — the sidecar profile arms a serving
   drift monitor (fingerprint-checked).
3. Serve the TRAINING rows back: predictions must be byte-identical
   to a direct ``Booster.predict`` and every drift score must sit
   well under ``quality_psi_warn`` (the zero-drift gate).
4. Serve a deliberately shifted stream: the shifted feature's PSI
   must blow past the warn threshold, the warn-once fires, and the
   ``ltpu_quality_*`` gauges must be present in the Prometheus text.
5. The operator report CLI must agree (rc 1 + the drifted feature
   named).

Writes ``/tmp/lgbtpu_smoke/quality.json``.

Usage: python scripts/quality_probe.py [out_json]
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PARAMS = {"objective": "regression", "verbose": -1, "num_leaves": 15,
          "min_data_in_leaf": 5, "quality": "on"}
SHIFT_FEATURE = 2
SHIFT = 8.0


def probe(work: str) -> dict:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.quality import profile_path
    from lightgbm_tpu.quality.__main__ import main as report_main
    from lightgbm_tpu.serving import ModelRegistry
    from lightgbm_tpu.telemetry import TELEMETRY

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    rng = np.random.RandomState(0)
    X = rng.randn(800, 6)
    y = X[:, 0] - 0.4 * X[:, 1]
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), 5,
                    verbose_eval=False)
    model = os.path.join(work, "quality_model.txt")
    bst.save_model(model)
    assert os.path.exists(profile_path(model)), \
        "quality=on training did not persist the profile sidecar"

    cfg = Config.from_params({"verbose": -1,
                              "quality_sample_rate": 1.0,
                              "quality_psi_warn": 0.2})
    reg = ModelRegistry(cfg)
    out: dict = {"profile": os.path.basename(profile_path(model))}
    try:
        entry = reg.publish("qm", model)
        assert entry.monitor is not None, "monitor did not arm"
        out["profile_features"] = len(entry.monitor.profile.features)

        # in-distribution traffic: byte parity + zero drift
        _, served = reg.predict("qm", X)
        direct = np.asarray(entry.booster.predict(X)).reshape(-1)
        parity = np.array_equal(np.asarray(served).reshape(-1), direct)
        out["parity"] = "pass" if parity else "FAIL"
        assert entry.monitor.wait_observed(len(X)), "observer stalled"
        rep = entry.monitor.report()
        out["in_dist_worst_psi"] = rep["worst_feature_psi"]
        out["in_dist_score_psi"] = rep["score_psi"]
        out["in_dist_leaf_psi"] = rep["leaf_psi"]
        assert rep["worst_feature_psi"] < 0.05, (
            "in-distribution traffic reads as drifted: "
            f"{rep['worst_feature_psi']}")
        assert rep["score_psi"] < 0.05 and rep["leaf_psi"] < 0.05, rep
        assert not rep["warned"]

        # deliberately shifted stream
        Xs = np.array(X)
        Xs[:, SHIFT_FEATURE] += SHIFT
        reg.predict("qm", Xs)
        assert entry.monitor.wait_observed(2 * len(X)), \
            "observer stalled"
        rep = entry.monitor.report()
        out["shifted_worst_feature"] = rep["worst_feature"]
        out["shifted_worst_psi"] = rep["worst_feature_psi"]
        out["warn_fired"] = bool(rep["warned"])
        assert rep["worst_feature"] == SHIFT_FEATURE, rep
        assert rep["worst_feature_psi"] > cfg.quality_psi_warn
        out["sampled_rows"] = rep["sampled_rows"]

        prom = TELEMETRY.to_prometheus()
        gauges = [ln.split()[0] for ln in prom.splitlines()
                  if ln.startswith("ltpu_quality_")]
        out["prom_gauges"] = sorted({g.split("{")[0] for g in gauges})
        assert any("worst_feature_psi" in g for g in gauges), gauges
        q = reg.describe()["qm"]["quality"]
        assert q["worst_feature"] == f"f{SHIFT_FEATURE}"
        out["models_quality_block"] = "pass"
    finally:
        reg.close()

    # operator report CLI agrees: rc 1 + the drifted feature named
    cur = os.path.join(work, "quality_current.csv")
    np.savetxt(cur, np.column_stack([y, Xs]), delimiter=",")
    rep_path = os.path.join(work, "quality_report.json")
    rc = report_main(["report", profile_path(model), cur,
                      "-o", rep_path, "verbose=-1"])
    rep = json.load(open(rep_path))
    assert rc == 1, f"report rc {rc} on drifted data"
    assert SHIFT_FEATURE in rep["drifted_features"] \
        or str(SHIFT_FEATURE) in [str(j) for j in
                                  rep["drifted_features"]], rep
    out["report_cli"] = "pass"
    return out


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 \
        else "/tmp/lgbtpu_smoke/quality.json"
    work = os.path.dirname(os.path.abspath(out_path)) or "."
    os.makedirs(work, exist_ok=True)
    out = probe(work)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(f"quality probe ok: in-dist worst PSI "
          f"{out['in_dist_worst_psi']:g}, shifted f"
          f"{out['shifted_worst_feature']} PSI "
          f"{out['shifted_worst_psi']:g}, {len(out['prom_gauges'])} "
          f"gauge families -> {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
