"""The leaf-partition Pallas kernel: split-leaf streams, routed and
compacted into tile-aligned child spans.

TPU redesign of DataPartition::Split
(reference src/treelearner/data_partition.hpp:109-161).  One sequential
grid walks the blocks of every leaf splitting this round (a prefetched
step table maps grid steps to (parent, block)); each 128-column tile is
routed (numeric split rules in feature-bin space), then left-going
columns are compacted FORWARD from the left child's alloc start and
right-going columns BACKWARD from the right child's alloc end — the
backward fill makes write cursors independent of the (unknown until
done) left count.  Compaction is a one-hot permutation matmul per tile
(Mosaic has no dynamic lane gather/scatter; at 128-lane granularity
with a 64-row carrier the matmul costs ~2*64*128*256 int8 ops per
tile, ~25% of a histogram pass on the same columns).  Dead columns
(alloc slack, tile padding) carry leaf = -1 and match nothing
downstream — spans only need to COVER the live columns, so children
need no intra-tile contiguity and no cross-parent coordination.

Flushes accumulate full (R, 128) tiles into a double-buffered staging
scratch DMA'd at dynamic tile offsets of the (T, R, 128) destination
carrier (the paged-attention pattern; dynamic offsets on the MINOR dim
crash Mosaic — scripts/kbench_probes2.py).

Step table columns (all int32):
  0 block      src block index (units of BT tiles); tail steps repeat
               the previous block so the pipeline skips the refetch
  1 first      1 = first step of its parent (reset stream state)
  2 last       1 = last step of its parent (final flushes)
  3 p_slot     parent leaf id ( == left child id)
  4 p_rslot    right child leaf id
  5 grp        split feature's group row
  6 thr        bin threshold
  7 dleft      default_left
  8 mtype      missing type (ops/partition.py constants)
  9 dbin       default bin
  10 nbin      feature num_bin
  11 fb_lo 12 fb_hi 13 fb_shift 14 fb_oor   group->feature bin affine
  15 dstL_t0   left child alloc start tile
  16 dstR_te   right child alloc end tile (exclusive)
  17 active    0 = tail padding step
  18 span_t0 19 span_te   parent's src span (tiles): block tiles
               outside it are SKIPPED — stale bytes beyond a span can
               alias any live slot id (unwritten alloc gaps, previous
               trees' leftovers); span tiles themselves are always
               fresh (fully written when the parent was created)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from carrier import CARRIER_ROWS, TILE, carrier_row_map
from lightgbm_tpu.ops.partition import MISSING_NAN, MISSING_ZERO

BT = 16            # tiles per block (block = 2048 columns)
STAGE = 8          # tiles per staging buffer flush

# SMEM state slots
_FILL_L, _FILL_R, _SN_L, _SN_R, _CUR_L, _CUR_R, _SEL_L, _SEL_R, \
    _OUT_L, _OUT_R = range(10)

NCOLS_TAB = 20


def _partition_body(tab_ref, src_ref, dst_in_ref, dst_ref, pendL, pendR,
                    stageL, stageR, smem, semL, semR, semres, *,
                    num_groups, rm, debug=0):
    del dst_in_ref  # aliased with dst_ref (same buffer)
    i = pl.program_id(0)
    active = tab_ref[i, 17] == 1
    first = tab_ref[i, 1] == 1
    last = tab_ref[i, 2] == 1
    p_slot = tab_ref[i, 3]
    p_rslot = tab_ref[i, 4]
    grp = tab_ref[i, 5]
    thr = tab_ref[i, 6]
    dleft = tab_ref[i, 7]
    mtype = tab_ref[i, 8]
    dbin = tab_ref[i, 9]
    nbin = tab_ref[i, 10]
    fb_lo = tab_ref[i, 11]
    fb_hi = tab_ref[i, 12]
    fb_shift = tab_ref[i, 13]
    fb_oor = tab_ref[i, 14]
    dstL_t0 = tab_ref[i, 15]
    dstR_te = tab_ref[i, 16]
    span_t0 = tab_ref[i, 18]
    span_te = tab_ref[i, 19]
    blk = tab_ref[i, 0]

    # dead-column pattern for final partial tiles: leaf rows -1, rest 0
    # (built from iota — pallas kernels cannot capture array constants)
    riota = jax.lax.broadcasted_iota(jnp.int32, (CARRIER_ROWS, TILE), 0)
    # computed in int32 then cast: an i1-from-int32-compare select with
    # int8 operands needs a replicated->tiled relayout Mosaic rejects
    dead_tile = jnp.where(
        riota == rm["leaf_lo"], -1,
        jnp.where(riota == rm["leaf_hi"], -1, 0)).astype(jnp.int8)
    liota = jax.lax.broadcasted_iota(jnp.int32, (1, TILE), 1)
    d_iota = jax.lax.broadcasted_iota(jnp.int32, (2 * TILE, TILE), 0)

    @pl.when(active & first)
    def _reset():
        pendL[:] = jnp.zeros_like(pendL)
        pendR[:] = jnp.zeros_like(pendR)
        smem[_FILL_L] = 0
        smem[_FILL_R] = 0
        smem[_SN_L] = 0
        smem[_SN_R] = 0
        smem[_CUR_L] = dstL_t0
        smem[_CUR_R] = dstR_te
        smem[_SEL_L] = 0
        smem[_SEL_R] = 0
        # outstanding-DMA flags for the two stage buffers, packed as
        # bit0/bit1 per side
        smem[_OUT_L] = 0
        smem[_OUT_R] = 0

    def emit(side_is_l, tile_val):
        """Side-dispatched staging append + flush (traced twice,
        statically, once per side)."""
        if side_is_l:
            stage, sem = stageL, semL
            k_sn, k_sel, k_cur, k_out = _SN_L, _SEL_L, _CUR_L, _OUT_L
        else:
            stage, sem = stageR, semR
            k_sn, k_sel, k_cur, k_out = _SN_R, _SEL_R, _CUR_R, _OUT_R
        sn = smem[k_sn]
        sel = smem[k_sel]
        slot = sn if side_is_l else STAGE - 1 - sn
        stage[sel, pl.ds(slot, 1)] = tile_val[None]
        smem[k_sn] = sn + 1

        @pl.when(sn + 1 == STAGE)
        def _flush():
            cur = smem[k_cur]
            t0 = cur if side_is_l else cur - STAGE
            # reusing this buffer after the flip requires its previous
            # DMA to have completed
            out = smem[k_out]
            nxt = 1 - sel

            @pl.when((out & (1 << nxt)) != 0)
            def _wait_prev():
                pltpu.make_async_copy(
                    stage.at[nxt], dst_ref.at[pl.ds(smem[k_cur], STAGE)],
                    sem.at[nxt]).wait()
            # (the wait target slice is irrelevant for wait(); the
            # semaphore identifies the transfer)
            cp = pltpu.make_async_copy(
                stage.at[sel], dst_ref.at[pl.ds(t0, STAGE)], sem.at[sel])
            cp.start()
            # clear the waited buffer's bit, set ours (a stale bit
            # would make the parent-end drain wait a second time on a
            # semaphore with no pending signal -> deadlock/crash)
            smem[k_out] = (out & ~(1 << nxt)) | (1 << sel)
            smem[k_cur] = cur + STAGE if side_is_l else cur - STAGE
            smem[k_sel] = nxt
            smem[k_sn] = 0

    def compact(tile_val, keep, pend, k_fill, side_is_l):
        """Route one side's columns of a tile into its pending buffer.

        Lane-oriented throughout (Mosaic rejects 1-lane dot outputs):
        exclusive prefix sum by log-shift adds, then a (2C, 128) 0/1
        destination matrix Q (Q[d, s] = dest[s]==d & keep[s]) built
        from sublane-iota compares, contracted with the tile on the
        int8 MXU.  Unfilled pending lanes stay 0 (the one-hot matmul
        contributes nothing there); only the FINAL partial flush must
        overwrite them with the dead pattern."""
        x = keep.astype(jnp.int32)                       # (1, 128)
        if debug == 2:       # compaction floor: dot into a fixed window
            contrib0 = jax.lax.dot_general(
                tile_val, jnp.broadcast_to(x, (2 * TILE, TILE))
                .astype(jnp.int8), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            pend[:] = pend[:] + contrib0
            return
        incl = x
        for k in (1, 2, 4, 8, 16, 32, 64):
            shifted = jnp.roll(incl, k, axis=1)
            incl = incl + jnp.where(liota >= k, shifted, 0)
        pos = incl - x                                   # exclusive
        fill = smem[k_fill]
        dest = pos + fill                                # (1, 128)
        q = ((jnp.broadcast_to(dest, (2 * TILE, TILE)) == d_iota)
             & jnp.broadcast_to(keep, (2 * TILE, TILE))).astype(jnp.int8)
        contrib = jax.lax.dot_general(                   # (R, 2C) i32
            tile_val, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        pend[:] = pend[:] + contrib
        k = jnp.sum(x)
        newfill = fill + k

        @pl.when(newfill >= TILE)
        def _spill():
            emit(side_is_l, pend[:, :TILE].astype(jnp.int8))
            pend[:, :TILE] = pend[:, TILE:]
            pend[:, TILE:] = jnp.zeros_like(pend[:, TILE:])
            smem[k_fill] = newfill - TILE

        @pl.when(newfill < TILE)
        def _nosp():
            smem[k_fill] = newfill

    def _do_tile(j):
            tile = src_ref[j]                            # (R, 128) i8
            lo = tile[rm["leaf_lo"], :].astype(jnp.int32) & 255
            hi = tile[rm["leaf_hi"], :].astype(jnp.int32)
            leaf = (lo | (hi << 8))[None, :]             # (1, 128)
            mask = leaf == p_slot
            # chosen group's bin per column: masked sum over the bins
            # rows (dynamic sublane reads need 8-alignment in Mosaic)
            binb = tile[:num_groups, :].astype(jnp.int32) & 255
            giota = jax.lax.broadcasted_iota(
                jnp.int32, (num_groups, TILE), 0)
            gb = jnp.sum(jnp.where(giota == grp, binb, 0), axis=0,
                         keepdims=True)                  # (1, 128)
            fbin = jnp.where((gb >= fb_lo) & (gb < fb_hi), gb - fb_shift,
                             fb_oor)
            is_nan_bin = fbin == nbin - 1
            is_def_bin = fbin == dbin
            cmp_left = (fbin <= thr).astype(jnp.int32)
            dl = dleft
            num_left = jnp.where(
                (mtype == MISSING_NAN) & is_nan_bin, dl,
                jnp.where((mtype == MISSING_ZERO) & is_def_bin, dl,
                          cmp_left))
            go_left = num_left > 0
            keepL = mask & go_left
            keepR = mask & ~go_left
            # right-bound columns take the right child's leaf id
            rlo = (p_rslot & 255).astype(jnp.int8)
            rhi = (p_rslot >> 8).astype(jnp.int8)
            tile_r = jnp.where(riota == rm["leaf_lo"], rlo,
                               jnp.where(riota == rm["leaf_hi"], rhi,
                                         tile))
            if debug == 1:       # route-only floor: consume the masks
                pendL[:1, :TILE] = pendL[:1, :TILE] + keepL.astype(
                    jnp.int32)
                pendR[:1, :TILE] = pendR[:1, :TILE] + keepR.astype(
                    jnp.int32)
            else:
                compact(tile, keepL, pendL, _FILL_L, True)
                compact(tile_r, keepR, pendR, _FILL_R, False)

    @pl.when(active)
    def _work():
        for j in range(BT):
            gt = blk * BT + j                # global tile index

            @pl.when((gt >= span_t0) & (gt < span_te))
            def _tile(j=j):
                _do_tile(j)
    @pl.when(active & last)
    def _finalize():
        # final partial pending tiles: lanes beyond fill carry zeros
        # (which would read as live leaf 0) — overwrite with the dead
        # pattern before emitting
        lanes = jnp.broadcast_to(liota, (CARRIER_ROWS, TILE))

        @pl.when(smem[_FILL_L] > 0)
        def _():
            tile = jnp.where(lanes >= smem[_FILL_L], dead_tile,
                             pendL[:, :TILE].astype(jnp.int8))
            emit(True, tile)

        @pl.when(smem[_FILL_R] > 0)
        def _():
            tile = jnp.where(lanes >= smem[_FILL_R], dead_tile,
                             pendR[:, :TILE].astype(jnp.int8))
            emit(False, tile)

        # residual staging (sn < STAGE tiles): single-tile sync DMAs
        for side_is_l in (True, False):
            if side_is_l:
                stage, sem = stageL, semres
                k_sn, k_sel, k_cur = _SN_L, _SEL_L, _CUR_L
            else:
                stage, sem = stageR, semres
                k_sn, k_sel, k_cur = _SN_R, _SEL_R, _CUR_R
            sn = smem[k_sn]
            sel = smem[k_sel]
            cur = smem[k_cur]
            for s in range(STAGE):
                @pl.when(s < sn)
                def _(s=s, stage=stage, sel=sel, cur=cur, sem=sem,
                      side_is_l=side_is_l):
                    slot = s if side_is_l else STAGE - 1 - s
                    dstt = cur + s if side_is_l else cur - 1 - s
                    cp = pltpu.make_async_copy(
                        stage.at[sel, pl.ds(slot, 1)],
                        dst_ref.at[pl.ds(dstt, 1)], sem)
                    cp.start()
                    cp.wait()
        # drain outstanding big flushes before the next parent reuses
        # the buffers (and before kernel exit)
        for k_out, stage, sem, k_cur in ((_OUT_L, stageL, semL, _CUR_L),
                                         (_OUT_R, stageR, semR, _CUR_R)):
            out = smem[k_out]
            for b in (0, 1):
                @pl.when((out & (1 << b)) != 0)
                def _(b=b, stage=stage, sem=sem, k_cur=k_cur):
                    pltpu.make_async_copy(
                        stage.at[b], dst_ref.at[pl.ds(smem[k_cur],
                                                      STAGE)],
                        sem.at[b]).wait()
            smem[k_out] = 0


def allocate_children(alloc_t0, alloc_te, kl, kr, arena_ptr):
    """Gap-splitting child allocator (vectorized over the W parents).

    Children split the parent's 128-aligned alloc span: left child
    left-aligned, right child right-aligned, slack in the middle split
    proportionally to child sizes.  When ceil-rounding overflows the
    parent span (gap < 0), the split relocates to the arena tail with
    two tiles of fresh slack.  All quantities in TILES except kl/kr
    (columns).

    Returns (dstL_t0, dstR_te, X, new_arena_ptr) — X is the aligned
    boundary between the children's allocs.
    """
    valid = kl + kr > 0
    tl = (kl + TILE - 1) // TILE
    tr = (kr + TILE - 1) // TILE
    gap = (alloc_te - alloc_t0) - tl - tr
    fits = (gap >= 0) | ~valid
    fb_size = jnp.where(~fits & valid, tl + tr + 2, 0)
    fb_off = arena_ptr + jnp.cumsum(fb_size) - fb_size
    a_use = jnp.where(fits, alloc_t0, fb_off)
    e_use = jnp.where(fits, alloc_te, fb_off + fb_size)
    gap_use = (e_use - a_use) - tl - tr
    tot = jnp.maximum(kl + kr, 1)
    gap_l = (gap_use * kl) // tot
    x = a_use + tl + gap_l
    return a_use, e_use, x, arena_ptr + jnp.sum(fb_size)


def build_step_table(span_t0, span_te, route_cols, dstl_t0, dstr_te,
                     valid, cap):
    """Build the (cap, NCOLS_TAB) int32 step table for one launch.

    Args: per-parent (W,) arrays — src span tiles [span_t0, span_te),
    the 12 route scalar columns stacked as route_cols (W, 12) in table
    order (p_slot, p_rslot, grp, thr, dleft, mtype, dbin, nbin, fb_lo,
    fb_hi, fb_shift, fb_oor), child alloc anchors, and a validity
    mask.  ``cap`` is the static grid size; tail steps repeat the last
    real block with active=0.
    """
    b0 = span_t0 // BT
    nb = jnp.where(valid, (span_te + BT - 1) // BT - b0, 0)
    nb = jnp.maximum(nb, jnp.where(valid, 1, 0))
    cum = jnp.cumsum(nb)
    total = cum[-1]
    offs = cum - nb
    i = jnp.arange(cap, dtype=jnp.int32)
    pidx = jnp.searchsorted(cum, i, side="right").astype(jnp.int32)
    pidx = jnp.clip(pidx, 0, span_t0.shape[0] - 1)
    j = i - offs[pidx]
    active = (i < total).astype(jnp.int32)
    block = b0[pidx] + j
    # tail: repeat the last real block so the input pipeline skips the
    # fetch entirely
    last_real = jnp.maximum(total - 1, 0)
    last_block = block[last_real]
    block = jnp.where(active == 1, block, last_block)
    first = ((j == 0) & (active == 1)).astype(jnp.int32)
    last = ((j == nb[pidx] - 1) & (active == 1)).astype(jnp.int32)
    cols = [block, first, last]
    for k in range(12):
        cols.append(route_cols[pidx, k])
    cols.append(dstl_t0[pidx])
    cols.append(dstr_te[pidx])
    cols.append(active)
    cols.append(span_t0[pidx])
    cols.append(span_te[pidx])
    return jnp.stack(cols, axis=1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("num_groups", "grid_cap", "interpret", "debug"),
    donate_argnums=(1,))
def partition_round(src: jax.Array, dst: jax.Array, tab: jax.Array, *,
                    num_groups: int, grid_cap: int,
                    interpret: bool = False, debug: int = 0) -> jax.Array:
    """Run one round of leaf partitioning.

    Args:
      src: (T, R, 128) int8 carrier holding the splitting parents.
      dst: (T, R, 128) int8 carrier to write children into (donated;
        only the children's alloc spans are overwritten).
      tab: (grid_cap, NCOLS_TAB) int32 step table (see module doc).
    Returns the updated dst carrier.
    """
    t, r, _ = src.shape
    rm = carrier_row_map(num_groups)
    kern = functools.partial(_partition_body, num_groups=num_groups,
                             rm=rm, debug=debug)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid_cap,),
        in_specs=[
            pl.BlockSpec((BT, r, TILE), lambda i, tab: (tab[i, 0], 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((CARRIER_ROWS, 2 * TILE), jnp.int32),  # pendL
            pltpu.VMEM((CARRIER_ROWS, 2 * TILE), jnp.int32),  # pendR
            pltpu.VMEM((2, STAGE, CARRIER_ROWS, TILE), jnp.int8),
            pltpu.VMEM((2, STAGE, CARRIER_ROWS, TILE), jnp.int8),
            pltpu.SMEM((16,), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(dst.shape, dst.dtype),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(tab, src, dst)
    return out
