#!/usr/bin/env python
"""End-to-end distributed-tracing smoke (round 23, the bench_smoke
trace gate): drives the REAL serving stack in spans mode and proves
the causal chain the merge tool renders —

1. a client request carrying an ``X-Ltpu-Trace`` header gets the SAME
   trace id echoed back on the response (context accepted + minted),
2. the exported + merged Perfetto timeline contains the request's
   ``serve_request`` span AND a ``serve_dispatch`` span flow-linked to
   it (the micro-batcher's fan-in arrow), and
3. an injected dispatch stall (slow predict under an armed
   ``watchdog_serve_s``) lands in the fleet event journal as a
   ``stall`` event NAMING its seam and carrying the request's trace id
   — the 3am property: one grep from a latency alert to the seam that
   caused it.

Usage: python scripts/trace_probe.py [OUT.json]; rc 0 all gates pass.
Asserted by tests/test_bench_smoke.py on the JSON it writes.
"""
import http.client
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build_model(features=6, rows=200, iters=3):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(11)
    X = rng.randn(rows, features)
    y = X[:, 0] - 0.3 * X[:, 1]
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "num_leaves": 7, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), iters, verbose_eval=False)
    return bst, X


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    out_path = argv[0] if argv else ""
    tmp = os.path.dirname(out_path) or "/tmp"

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import ModelRegistry, ServingFrontend
    from lightgbm_tpu.telemetry import (TELEMETRY, TRACE_HEADER,
                                        merge_shards, new_span_id,
                                        new_trace_id)

    TELEMETRY.configure("spans")
    TELEMETRY.reset()
    bst, X = build_model()

    # injected stall seam: the probe flips `stall["s"]` and the next
    # dispatch sleeps past the armed watchdog_serve_s deadline
    stall = {"s": 0.0}
    orig = bst.predict

    def predict(rows, **kw):
        if stall["s"]:
            time.sleep(stall["s"])
        return orig(rows, **kw)

    bst.predict = predict

    cfg = Config.from_params({
        "verbose": -1,
        "serve_batch_deadline_ms": 1.0,
        "watchdog_serve_s": 0.15,
    })
    registry = ModelRegistry(cfg)
    registry.publish("probe", bst)
    frontend = ServingFrontend(registry, cfg)
    port = frontend.start(0).server_address[1]

    result = {"requests": 0}
    trace_id = new_trace_id()
    body = json.dumps({"rows": X[:2].tolist()}).encode()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)

    # gate 1: header round trip — the response names OUR trace
    conn.request("POST", "/predict/probe", body=body, headers={
        "Content-Type": "application/json",
        TRACE_HEADER: f"{trace_id}-{new_span_id()}"})
    resp = conn.getresponse()
    resp.read()
    result["requests"] += 1
    echoed = resp.getheader(TRACE_HEADER) or ""
    result["status"] = resp.status
    result["header_echo"] = ("pass" if resp.status == 200
                             and echoed.startswith(trace_id + "-")
                             else "fail")

    # gate 3 setup: a stalled dispatch under the armed serve watchdog
    # (expected to FAIL the request — the journal event is the point)
    stall["s"] = 0.5
    stall_trace = new_trace_id()
    try:
        conn.request("POST", "/predict/probe", body=body, headers={
            "Content-Type": "application/json",
            TRACE_HEADER: f"{stall_trace}-{new_span_id()}"})
        resp = conn.getresponse()
        resp.read()
        result["stall_status"] = resp.status
    except Exception as e:  # noqa: BLE001 - conn may die on the 500
        result["stall_status"] = repr(e)
    stall["s"] = 0.0
    conn.close()
    frontend.stop(drain=True)

    # export one shard + merge it — the same path a fleet run takes
    TELEMETRY.mark_sync()
    prefix = os.path.join(tmp, "trace_telemetry")
    TELEMETRY.export(prefix)
    merged = merge_shards([prefix + ".jsonl"])
    events = merged["traceEvents"]

    # gate 2: the request span and a flow-linked dispatch span
    req_spans = [e for e in events if e.get("name") == "serve_request"
                 and (e.get("args") or {}).get("trace") == trace_id]
    disp_spans = [e for e in events if e.get("name") == "serve_dispatch"
                  and (e.get("args") or {}).get("trace") == trace_id]
    member_span = (req_spans[0]["args"].get("span")
                   if req_spans else None)
    linked = any(member_span and member_span in
                 ((e.get("args") or {}).get("links") or [])
                 for e in disp_spans)
    result["flow_links"] = merged["metadata"].get("flow_links", 0)
    result["flow_link"] = ("pass" if req_spans and disp_spans and
                           linked and result["flow_links"] >= 1
                           else "fail")

    # gate 3: the stall journaled, naming its seam + the trace id
    stall_events = [e for e in events
                    if e.get("cat") == "journal"
                    and str(e.get("name", "")).startswith("stall")]
    named = [e for e in stall_events
             if (e.get("args") or {}).get("seam") == "predict.dispatch"
             and (e.get("args") or {}).get("trace") == stall_trace]
    result["stall_journal"] = "pass" if named else "fail"
    result["journal_instants"] = len(
        [e for e in events if e.get("cat") == "journal"])

    ok = all(result.get(k) == "pass" for k in
             ("header_echo", "flow_link", "stall_journal"))
    result["status_overall"] = "pass" if ok else "fail"
    text = json.dumps(result, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
        print(f"trace_probe: header_echo {result['header_echo']}, "
              f"flow_link {result['flow_link']} "
              f"({result['flow_links']} arrow(s)), stall_journal "
              f"{result['stall_journal']} -> {out_path}",
              file=sys.stderr)
    else:
        print(text)
    TELEMETRY.configure("off")
    TELEMETRY.reset()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
