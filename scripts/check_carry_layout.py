#!/usr/bin/env python
"""Carry-layout lint: tree.TREE_RECORD_SPEC vs the grower's emit sites.

The packed single-buffer tree carry (round 7) serializes a grown
TreeArrays into one uint8 record at FIXED offsets
(tree.TreeRecordLayout).  Three places must agree on that layout —

- the spec itself (lightgbm_tpu/tree.py TREE_RECORD_SPEC),
- the grower's TreeArrays fields and the dtypes it materializes in
  `_init_state` (lightgbm_tpu/learner/grower.py), and
- the unpack sites (host `unpack_tree_record`, device
  `ops/predict.py unpack_tree_records_device`)

— and a field added to TreeArrays without a matching spec row (or with
a different dtype) would silently drop or corrupt tree state only on
the packed path.  This lint fails on any drift; scripts/bench_smoke.sh
runs it before the bench so CI catches it without a training run.

Checks:
  1. spec field names/order == TreeArrays._fields (exact),
  2. every dtype the grower materializes in `_init_state` maps to the
     spec dtype (jnp.int32 -> <i4, jnp.float32 -> <f4, bool -> |u1),
     parsed from the grower SOURCE so a dtype edit at the emit site
     trips the lint even if nothing imports,
  3. offsets are word-aligned, non-overlapping, monotonic; record is
     64-byte padded,
  4. functional round-trip: pack a randomized TreeArrays on the CPU
     backend, unpack host-side AND device-side, require exact equality
     field by field.

Usage: python scripts/check_carry_layout.py   (rc 0 clean, rc 1 drift)
"""
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

ERRORS = []


def err(msg):
    ERRORS.append(msg)
    print(f"DRIFT: {msg}", file=sys.stderr)


# dtype token the grower writes at the emit site -> spec dtype string
GROWER_DTYPE_TO_SPEC = {
    "jnp.int32": "<i4",
    "jnp.float32": "<f4",
    "bool": "|u1",
}


def check_field_order(spec, tree_arrays_cls):
    spec_names = [name for name, _, _ in spec]
    fields = list(tree_arrays_cls._fields)
    if spec_names != fields:
        err(f"TREE_RECORD_SPEC field order {spec_names} != "
            f"TreeArrays._fields {fields}")


def check_grower_emit_dtypes(spec):
    """Parse `_init_state`'s TreeArrays(...) literal for each field's
    dtype token and compare against the spec."""
    src_path = os.path.join(REPO, "lightgbm_tpu", "learner", "grower.py")
    with open(src_path) as f:
        src = f.read()
    m = re.search(r"tree = TreeArrays\((.*?)\n\s*\)", src, re.S)
    if not m:
        err("could not find the `tree = TreeArrays(...)` emit site in "
            "learner/grower.py _init_state")
        return
    body = m.group(1)
    # split the literal's kwargs on top-level commas (nested parens in
    # shape tuples rule out a flat regex)
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    emitted = {}
    for part in parts:
        if "=" not in part:
            continue
        name, expr = part.split("=", 1)
        name, expr = name.strip(), expr.strip()
        if not re.fullmatch(r"\w+", name):
            continue
        if name == "num_leaves":
            # scalar: jnp.int32(1)
            emitted[name] = "<i4" if "jnp.int32" in expr else "?"
            continue
        toks = [t for t in GROWER_DTYPE_TO_SPEC
                if re.search(rf"[,(]\s*{re.escape(t)}\s*[,)]", expr)]
        emitted[name] = GROWER_DTYPE_TO_SPEC[toks[0]] if len(toks) == 1 \
            else "?"
    for name, dt, _ in spec:
        if name not in emitted:
            err(f"spec field {name!r} has no emit site in "
                f"grower._init_state")
        elif emitted[name] == "?":
            err(f"could not determine the dtype grower._init_state "
                f"materializes for {name!r}")
        elif emitted[name] != dt:
            err(f"{name!r}: grower emits {emitted[name]}, spec says "
                f"{dt}")
    for name in emitted:
        if name not in {n for n, _, _ in spec}:
            err(f"grower emits field {name!r} with no spec row — it "
                f"would be DROPPED by the packed carry")


def check_offsets(layout):
    prev_end = 0
    for name, (off, nbytes, dt, shape) in layout.fields.items():
        if off % 4:
            err(f"{name!r}: offset {off} not word-aligned")
        if off < prev_end:
            err(f"{name!r}: offset {off} overlaps previous field "
                f"(ends at {prev_end})")
        prev_end = off + nbytes
    if layout.record_size % 64:
        err(f"record_size {layout.record_size} not 64-byte padded")
    if prev_end > layout.record_size:
        err(f"fields end at {prev_end} past record_size "
            f"{layout.record_size}")


def check_roundtrip(layout, tree_arrays_cls, spec):
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import unpack_tree_records_device

    rng = np.random.RandomState(7)
    vals = {}
    for name, (off, nbytes, dt, shape) in layout.fields.items():
        kind = np.dtype(dt).kind
        if name == "num_leaves":
            vals[name] = jnp.int32(5)
        elif kind == "u":
            vals[name] = jnp.asarray(rng.rand(*shape) > 0.5)
        elif kind == "i":
            vals[name] = jnp.asarray(
                rng.randint(-100, 100, size=shape), jnp.int32)
        else:
            vals[name] = jnp.asarray(
                rng.randn(*shape).astype(np.float32))
    tree = tree_arrays_cls(**vals)
    rec = np.asarray(jax.jit(layout.pack_tree_record)(tree))

    host = layout.unpack_tree_record(rec)
    for name, _, _ in spec:
        want = np.asarray(vals[name])
        got = np.asarray(host[name])
        if got.shape != want.shape or not np.array_equal(got, want):
            err(f"host round-trip mismatch on {name!r}")

    dev = unpack_tree_records_device(
        jnp.asarray(rec), layout.num_leaves, layout.max_feature_bin)
    for name, _, _ in spec:
        got = np.asarray(getattr(dev, name))
        want = np.asarray(vals[name])
        if got.shape != want.shape or not np.array_equal(got, want):
            err(f"device round-trip mismatch on {name!r}")


def main():
    from lightgbm_tpu.tree import TREE_RECORD_SPEC, TreeRecordLayout
    from lightgbm_tpu.learner.grower import TreeArrays

    check_field_order(TREE_RECORD_SPEC, TreeArrays)
    check_grower_emit_dtypes(TREE_RECORD_SPEC)
    for L, B in ((31, 64), (8, 16)):
        layout = TreeRecordLayout(L, B)
        check_offsets(layout)
    check_roundtrip(TreeRecordLayout(8, 16), TreeArrays,
                    TREE_RECORD_SPEC)

    if ERRORS:
        print(f"check_carry_layout: {len(ERRORS)} drift error(s)",
              file=sys.stderr)
        return 1
    print("check_carry_layout: spec, grower emit sites, offsets and "
          "pack/unpack round-trip all consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
