#!/usr/bin/env python
"""Carry-layout lint: tree.TREE_RECORD_SPEC vs the grower's emit sites.

Thin wrapper over analysis rule ``CARRY001``
(lightgbm_tpu/analysis/layout_rule.py) — the check logic was re-homed
into the `python -m lightgbm_tpu.analysis` engine in the
static-analysis round; this entry point keeps the historical CLI
contract (rc 0 clean, rc 1 drift, findings on stderr) for tooling that
calls it directly.  ``scripts/bench_smoke.sh`` now runs the full
analysis suite instead.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    from lightgbm_tpu.analysis import run_rules, unsuppressed
    findings = run_rules(["CARRY001"], check_suppressions=False)
    live = unsuppressed(findings)
    for f in live:
        print(f"DRIFT: {f.message}", file=sys.stderr)
    if live:
        print(f"check_carry_layout: {len(live)} drift error(s)",
              file=sys.stderr)
        return 1
    print("check_carry_layout: spec, grower emit sites, offsets and "
          "pack/unpack round-trip all consistent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
