"""Leaf-partitioned carrier: the TPU redesign of DataPartition.

The reference groups row INDICES contiguously by leaf and gathers
feature bytes through them (src/treelearner/data_partition.hpp:109-161)
— free on a cache-hierarchy CPU, dead on TPU (XLA row gather measured
36 GB/s vs a 534 GB/s stream, scripts/kbench_gather.py).  Instead the
per-row DATA physically rides the partition: everything a tree round
touches lives in one int8 "carrier" laid out as (T, R, 128) — T
128-column tiles of R byte-rows per column — and splitting a leaf
streams its tiles once, routing each column and compacting left/right
children into fresh tile-aligned spans (ops/partition_kernel.py).
Histogram passes then stream ONLY the frontier leaves' spans: per-pass
cost becomes proportional to the split leaves' sizes (Σ≈8N per tree,
Σ smaller-child ≈3N) instead of rounds × N.

Column byte-rows (R = 64):
  0..G-1      packed group bins (uint8 bytes)
  G..G+2      quantized weights: grad_q, hess_q (int8), cnt (0/1)
  G+3, G+4    leaf id, little-endian int16 (lo byte, SIGN-carrying hi
              byte: -1 == dead column — alloc padding / tile slack)
  G+5..G+8    perm: original row index, int32 LE (bagging hash seed,
              debugging)
  G+9..G+12   score, f32 bits LE
  G+13..G+16  label, f32 bits LE
  G+17..G+20  sample weight, f32 bits LE (ones when unweighted)

Order-free training state: scores/labels/weights permute WITH the data
so gradients, metrics and score updates are computed in "current
order" — nothing ever needs the original row order back (objectives
and metrics are row-order-invariant reductions; bagging re-derives
masks from the carried perm row).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

TILE = 128
CARRIER_ROWS = 64


def carrier_row_map(num_groups: int) -> dict:
    g = num_groups
    if g + 21 > CARRIER_ROWS:
        raise ValueError(
            f"carrier supports at most {CARRIER_ROWS - 21} feature "
            f"groups, got {g}")
    return dict(bins=0, wq=g, leaf_lo=g + 3, leaf_hi=g + 4, perm=g + 5,
                score=g + 9, label=g + 13, weight=g + 17)


def _f32_rows(x: jax.Array) -> jax.Array:
    """(N,) f32 -> (4, N) int8 little-endian byte rows (bit-exact)."""
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    return jnp.stack([(bits >> (8 * i)).astype(jnp.int8)
                      for i in range(4)])


def _i32_rows(x: jax.Array) -> jax.Array:
    return jnp.stack([(x >> (8 * i)).astype(jnp.int8) for i in range(4)])


def rows_to_f32(rows: jax.Array) -> jax.Array:
    """(4, N) int8 byte rows -> (N,) f32 (inverse of _f32_rows)."""
    b = [rows[i].astype(jnp.int32) & 255 for i in range(4)]
    bits = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    return jax.lax.bitcast_convert_type(bits, jnp.float32)


def rows_to_i32(rows: jax.Array) -> jax.Array:
    b = [rows[i].astype(jnp.int32) & 255 for i in range(4)]
    return b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)


def rows_to_leaf(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """lo/hi int8 rows -> int32 leaf ids (hi carries the sign)."""
    return (lo.astype(jnp.int32) & 255) | (hi.astype(jnp.int32) << 8)


def leaf_to_rows(leaf: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return leaf.astype(jnp.int8), (leaf >> 8).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("num_tiles", "num_groups"))
def assemble_carrier(bins: jax.Array, score: jax.Array, label: jax.Array,
                     weight: jax.Array, *, num_tiles: int,
                     num_groups: int) -> jax.Array:
    """Build the canonical (T, R, 128) carrier from original-order
    arrays.  ``bins`` is (N, G) uint8; N-padded/cap-padded columns are
    dead (leaf = -1).  wq rows start zeroed (filled per tree)."""
    n = bins.shape[0]
    ncap = num_tiles * TILE
    rm = carrier_row_map(num_groups)
    rows = jnp.zeros((CARRIER_ROWS, ncap), jnp.int8)

    def put(r, arr):
        return jax.lax.dynamic_update_slice(rows, arr, (r, 0))

    pad = ncap - n
    binsT = jnp.pad(bins.astype(jnp.int8).T, ((0, 0), (0, pad)))
    rows = jax.lax.dynamic_update_slice(rows, binsT, (rm["bins"], 0))
    leaf = jnp.concatenate([jnp.zeros(n, jnp.int32),
                            jnp.full(pad, -1, jnp.int32)])
    lo, hi = leaf_to_rows(leaf)
    rows = put(rm["leaf_lo"], lo[None, :])
    rows = put(rm["leaf_hi"], hi[None, :])
    rows = put(rm["perm"], _i32_rows(
        jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, pad))))
    rows = put(rm["score"], _f32_rows(jnp.pad(score, (0, pad))))
    rows = put(rm["label"], _f32_rows(jnp.pad(label, (0, pad))))
    rows = put(rm["weight"], _f32_rows(jnp.pad(weight, (0, pad))))
    return rows.reshape(CARRIER_ROWS, num_tiles, TILE).transpose(1, 0, 2)


def carrier_get_row(carrier: jax.Array, row: int,
                    count: int = 4) -> jax.Array:
    """(T, R, 128) carrier -> (count, T*128) int8 row view."""
    t = carrier.shape[0]
    sl = jax.lax.dynamic_slice_in_dim(carrier, row, count, axis=1)
    return sl.transpose(1, 0, 2).reshape(count, t * TILE)


def carrier_set_rows(carrier: jax.Array, row: int,
                     rows: jax.Array) -> jax.Array:
    """Write (k, T*128) int8 rows back into the carrier."""
    t = carrier.shape[0]
    k = rows.shape[0]
    blk = rows.reshape(k, t, TILE).transpose(1, 0, 2)
    return jax.lax.dynamic_update_slice(carrier, blk, (0, row, 0))
