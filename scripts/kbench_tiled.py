"""Microbench: tiled-iota on-the-fly rebuild vs existing hist kernels.

Gate for the round-4 leaf-partitioned design: the partitioned layout
moves only narrow per-row data (bins, weights) and rebuilds the one-hot
in VMEM — viable only if the rebuild approaches the MXU floor
(~1.34 ms/pass at 1M x 28 x 63) instead of q_packed's rebuild cost.

D2H-sync timing (block_until_ready lies on axon), two loop counts to
cancel dispatch overhead.  All device arrays are threaded as jit
ARGUMENTS (closures inline as MLIR constants and blow the remote
compile request limit).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import (
    PACKED_STRIP, compute_group_histograms_pre_packed,
    compute_group_histograms_q_packed, compute_group_histograms_q_tiled,
    precompute_bin_onehot_packed)

L1, L2 = 20, 100


def loop_time(call, *args):
    times = {}
    for loops in (L1, L2):
        @jax.jit
        def many(*a):
            def body(i, carry):
                acc, s = carry
                h = call(s, *a)
                v = h[0, 0, 0, 0]
                bump = jnp.where(jnp.isfinite(v), 0, 1).astype(jnp.int32)
                return acc + v, jnp.roll(s + bump, i)
            out, _ = jax.lax.fori_loop(
                0, loops, body,
                (jnp.float32(0.0),
                 jnp.arange(PACKED_STRIP, dtype=jnp.int32)))
            return out
        _ = np.asarray(many(*args))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _ = np.asarray(many(*args))
            best = min(best, time.perf_counter() - t0)
        times[loops] = best
    return (times[L2] - times[L1]) / (L2 - L1)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_003_520
    g, b = 28, 63
    block = int(os.environ.get("BLOCK", 2048))
    rng = np.random.RandomState(0)
    bins_np = rng.randint(0, b, (n, g), dtype=np.uint8)
    bins = jnp.asarray(bins_np)
    binsT = jnp.asarray(bins_np.T)
    leaf = jnp.asarray(rng.randint(0, PACKED_STRIP, n, dtype=np.int32))
    wq_np = np.stack([rng.randint(-127, 128, n), rng.randint(0, 128, n),
                      np.ones(n)], axis=1).astype(np.int32)
    wq = jnp.asarray(wq_np)
    wT = jnp.asarray(wq_np.T)
    scales = jnp.ones(3, jnp.float32)
    slots = jnp.arange(PACKED_STRIP, dtype=jnp.int32)

    # correctness first
    h_ref = np.asarray(compute_group_histograms_q_packed(
        bins, wq, scales, leaf, slots, max_group_bin=b, block=block,
        strips=1))
    h_new = np.asarray(compute_group_histograms_q_tiled(
        binsT, wT, scales, leaf, slots, max_group_bin=b, block=block,
        strips=1))
    err = np.abs(h_new - h_ref).max()
    assert err == 0.0, f"tiled mismatch {err}"
    print("correctness OK")

    t = loop_time(
        lambda s, bT, w, lf: compute_group_histograms_q_tiled(
            bT, w, scales, lf, s, max_group_bin=b, block=block, strips=1),
        binsT, wT, leaf)
    print(f"q_tiled  (otf, new): {t*1e3:.2f} ms/pass")

    t = loop_time(
        lambda s, bn, w, lf: compute_group_histograms_q_packed(
            bn, w, scales, lf, s, max_group_bin=b, block=block, strips=1),
        bins, wq, leaf)
    print(f"q_packed (otf, old): {t*1e3:.2f} ms/pass")

    ohb = precompute_bin_onehot_packed(bins, max_group_bin=b, pack=4)
    t = loop_time(
        lambda s, o, w, lf: compute_group_histograms_pre_packed(
            o, w, scales, lf, s, max_group_bin=b, block=block, strips=1,
            quant=True, pack=4, num_groups=g),
        ohb, wq, leaf)
    print(f"pre_packed pack=4 (streamed): {t*1e3:.2f} ms/pass")

    for strips in (2, 3):
        s0 = jnp.arange(PACKED_STRIP * strips, dtype=jnp.int32)

        def call(s, bT, w, lf, st=strips, s0=s0):
            return compute_group_histograms_q_tiled(
                bT, w, scales, lf, s0 + s[0] * 0, max_group_bin=b,
                block=block, strips=st)

        t = loop_time(call, binsT, wT, leaf)
        print(f"q_tiled strips={strips}: {t*1e3:.2f} ms/pass")


if __name__ == "__main__":
    sys.exit(main())
