"""Checkpoint save/resume overhead probe + smoke fault-plan recovery.

Run by ``scripts/bench_smoke.sh`` and asserted by
``tests/test_bench_smoke.py``.  Three child runs of one tiny training
job (same deterministic data, ``checkpoint_freq=2``):

1. **cold**    — uninterrupted; yields the cold wall and the
   checkpoint-save telemetry (ms per snapshot).
2. **kill**    — ``LTPU_FAULT_PLAN=gbdt.train_chunk:3:kill`` SIGKILLs
   the process at the third fused-chunk dispatch (a real ``kill -9``
   through the fault harness, docs/RELIABILITY.md).
3. **resume**  — the same command again; auto-resumes from the newest
   valid checkpoint and must produce a byte-identical model.

Writes ``/tmp/lgbtpu_smoke/reliability.json``:
``save_ms_per_snapshot`` (the per-snapshot overhead series),
``resume_vs_cold_delta_s`` (wall saved by resuming instead of
retraining), ``kill_recovery`` ("pass"/"fail") and the raw runs.

Usage: python scripts/reliability_probe.py [out_json]
       python scripts/reliability_probe.py --child <model_out>
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ITERS = 8
CHUNK = 2


def child(out_model: str) -> None:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.telemetry import TELEMETRY
    TELEMETRY.configure("counters")
    rng = np.random.RandomState(11)
    X = rng.randn(600, 8)
    y = (X[:, 0] + 0.3 * rng.randn(600) > 0).astype(float)
    # verbose=1: the "Resumed training from checkpoint" info line (on
    # stderr) is how the parent PROVES the third run resumed rather
    # than deterministically retraining from scratch
    params = dict(objective="binary", num_leaves=15, max_bin=63,
                  verbose=1, dispatch_chunk=CHUNK, checkpoint_freq=2,
                  output_model=out_model, retry_backoff_s=0.0)
    t0 = time.perf_counter()
    bst = lgb.train(params, lgb.Dataset(X, label=y), ITERS,
                    verbose_eval=False)
    wall = time.perf_counter() - t0
    bst.save_model(out_model)
    c = TELEMETRY.counters()
    print(json.dumps({
        "wall_s": round(wall, 3),
        "trees": bst.num_trees(),
        "checkpoint_saves": c.get("checkpoint_saves", 0),
        "checkpoint_save_ms": round(c.get("checkpoint_save_ms", 0.0),
                                    3),
    }))


def run_child(out_model: str, fault_plan: str = ""):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("LTPU_FAULT_PLAN", None)
    if fault_plan:
        env["LTPU_FAULT_PLAN"] = fault_plan
    t0 = time.perf_counter()
    run = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         out_model],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    wall = time.perf_counter() - t0
    info = {}
    for line in (run.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            info = json.loads(line)
    return run.returncode, wall, info, run


def main() -> int:
    out_json = sys.argv[1] if len(sys.argv) > 1 \
        else "/tmp/lgbtpu_smoke/reliability.json"
    os.makedirs(os.path.dirname(out_json), exist_ok=True)
    work = os.path.join(os.path.dirname(out_json), "reliability_work")
    os.makedirs(work, exist_ok=True)
    cold_model = os.path.join(work, "cold.txt")
    kill_model = os.path.join(work, "kill.txt")
    for stale in (cold_model, kill_model):
        if os.path.exists(stale):
            os.unlink(stale)
        for ck in os.listdir(work):
            if ck.startswith(os.path.basename(stale) + ".ckpt"):
                os.unlink(os.path.join(work, ck))

    rc, cold_wall, cold_info, cold_run = run_child(cold_model)
    if rc != 0:
        sys.stderr.write(cold_run.stdout + cold_run.stderr)
        return 1
    saves = max(1, int(cold_info.get("checkpoint_saves", 0)))
    save_ms = cold_info.get("checkpoint_save_ms", 0.0) / saves

    # SIGKILL at the third fused-chunk dispatch: iterations 4..6 never
    # run; the newest valid checkpoint is iteration 4
    rc_kill, _, _, _ = run_child(kill_model,
                                 fault_plan="gbdt.train_chunk:3:kill")
    rc_res, resume_wall, res_info, res_run = run_child(kill_model)
    equal = False
    if rc_res == 0 and os.path.exists(kill_model):
        with open(cold_model) as a, open(kill_model) as b:
            equal = a.read() == b.read()
    resumed = "Resumed training from checkpoint" in (
        res_run.stdout + res_run.stderr)
    ok = rc_kill == -9 and rc_res == 0 and equal and resumed

    out = {
        "iters": ITERS,
        "dispatch_chunk": CHUNK,
        "checkpoint_saves": saves,
        "save_ms_per_snapshot": round(save_ms, 3),
        "cold_wall_s": round(cold_info.get("wall_s", cold_wall), 3),
        "resume_wall_s": round(res_info.get("wall_s", resume_wall), 3),
        # resuming retrains only the lost tail, so the in-train wall
        # should come in under the cold run's (noisy at smoke scale —
        # reported, not gated)
        "resume_vs_cold_delta_s": round(
            cold_info.get("wall_s", 0.0) - res_info.get("wall_s", 0.0),
            3),
        "kill_returncode": rc_kill,
        "byte_identical": equal,
        "kill_recovery": "pass" if ok else "fail",
    }
    with open(out_json, "w") as f:
        json.dump(out, f, indent=1)
    sys.stderr.write("reliability probe: " + json.dumps(out) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
