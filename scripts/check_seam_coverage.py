#!/usr/bin/env python
"""Seam-coverage lint (TEL001-style, two directions): every fault
seam registered in ``lightgbm_tpu/reliability/faults.py`` must be

1. EXERCISED — named by at least one test (``tests/*.py``) or probe
   (``scripts/*.py``, this lint excluded): a seam nothing injects
   into is a recovery path nothing has ever proven, and
2. DOCUMENTED — present in the docs/RELIABILITY.md seam-registry
   table: an undocumented seam is un-runbook-able at 3am,

and conversely every seam the RELIABILITY.md table documents must
still be registered — a documented-but-deleted seam means the doc
(and any chaos glob built on it) silently rotted.

A third direction (the fleet event journal): the shared fire path in
``FaultInjector.fault_point`` must journal every firing
(``journal.emit(`` in faults.py) — because ALL seams fire through
that one path, a static check that the call is present guarantees
every registered seam's firing lands in the journal; the per-seam
runtime proof lives in tests/test_tracing.py.

A fourth direction (transport lifecycle kinds, ISSUE 20): the TCP
transport must journal its recovery lifecycle — ``coordinator_change``
and ``reconnect`` (plus ``crc_error`` and ``membership_join``) emit
calls in ``parallel/transport.py``.  A failover or an in-epoch
reconnect that leaves no journal trail is undebuggable at 3am; the
runtime proof lives in tests/test_transport.py.

Runs in ``scripts/bench_smoke.sh`` before the bench; rc 0 clean,
rc 1 drift (findings on stderr), matching the check_carry_layout /
check_telemetry_coverage contract.  The seam registry is parsed
straight from the faults.py source (no package import — the lint
must stay sub-second with no jax in sight).
"""
import glob
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAULTS_PY = os.path.join(REPO, "lightgbm_tpu", "reliability",
                         "faults.py")
DOC = os.path.join(REPO, "docs", "RELIABILITY.md")
SELF = os.path.abspath(__file__)


def registered_seams():
    """The SEAMS tuple literal, parsed from source: quoted strings
    between ``SEAMS = (`` and the closing ``)`` at column 0."""
    with open(FAULTS_PY) as f:
        src = f.read()
    m = re.search(r"^SEAMS = \(\n(.*?)^\)\n", src, re.S | re.M)
    if not m:
        print("DRIFT: cannot locate the SEAMS registry tuple in "
              f"{FAULTS_PY}", file=sys.stderr)
        sys.exit(1)
    return re.findall(r'"([a-z_.]+)"', m.group(1))


def exercised_in():
    """{seam: [files naming it]} over tests/ + scripts/ (this lint
    and __pycache__ excluded)."""
    sources = {}
    for pat in ("tests/*.py", "scripts/*.py"):
        for path in glob.glob(os.path.join(REPO, pat)):
            if os.path.abspath(path) == SELF:
                continue
            with open(path) as f:
                sources[os.path.relpath(path, REPO)] = f.read()
    return sources


def documented_seams():
    """First-column backticked names of the RELIABILITY.md
    seam-registry table (rows like ``| `gbdt.train_chunk` | ... |``,
    dotted names only — other tables in the doc use knob names)."""
    with open(DOC) as f:
        text = f.read()
    return set(re.findall(r"^\|\s*`([a-z_]+\.[a-z_]+)`\s*\|", text,
                          re.M))


def journal_wired() -> bool:
    """Whether the shared fault fire path journals its firings: one
    ``journal.emit(`` call in faults.py covers every seam (they all
    fire through ``FaultInjector.fault_point``)."""
    with open(FAULTS_PY) as f:
        return "journal.emit(" in f.read()


TRANSPORT_PY = os.path.join(REPO, "lightgbm_tpu", "parallel",
                            "transport.py")
TRANSPORT_JOURNAL_KINDS = ("coordinator_change", "reconnect",
                           "crc_error", "membership_join")


def transport_journal_missing():
    """Transport lifecycle kinds with no ``journal.emit("<kind>"``
    call left in parallel/transport.py (the emit's kind argument is
    the first positional, possibly on the next line)."""
    with open(TRANSPORT_PY) as f:
        src = f.read()
    return [k for k in TRANSPORT_JOURNAL_KINDS
            if not re.search(r'journal\.emit\(\s*"%s"' % k, src)]


def main() -> int:
    seams = registered_seams()
    sources = exercised_in()
    documented = documented_seams()
    drift = []
    if not journal_wired():
        drift.append(
            "the fault fire path in reliability/faults.py no longer "
            "journals firings (journal.emit( missing) — chaos/fault "
            "events would vanish from the fleet event journal")
    for kind in transport_journal_missing():
        drift.append(
            f"parallel/transport.py no longer journals {kind!r} — "
            "the transport recovery lifecycle (failover/reconnect/"
            "integrity) would vanish from the fleet event journal")
    for seam in seams:
        users = [rel for rel, src in sources.items() if seam in src]
        if not users:
            drift.append(
                f"seam {seam!r} is registered but exercised by no "
                "test or probe — its recovery path is unproven "
                "(add a fault-plan test, or a chaos glob covering it)")
        if seam not in documented:
            drift.append(
                f"seam {seam!r} is registered but missing from the "
                "docs/RELIABILITY.md seam-registry table")
    for name in sorted(documented - set(seams)):
        drift.append(
            f"docs/RELIABILITY.md documents seam {name!r} which is "
            "not registered in reliability/faults.py — stale doc row")
    for d in drift:
        print(f"DRIFT: {d}", file=sys.stderr)
    if drift:
        print(f"check_seam_coverage: {len(drift)} drift error(s)",
              file=sys.stderr)
        return 1
    print(f"check_seam_coverage: {len(seams)} seams all exercised "
          "and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
