"""Generate docs/Parameters.md from the Config dataclass source.

The reference maintains docs/Parameters.rst by hand next to
include/LightGBM/config.h; here the parameter reference is DERIVED
from `lightgbm_tpu/config.py` (sections, fields, defaults, inline
comments, alias table) merged with the curated descriptions below —
`tests/test_docs.py` regenerates it and fails on drift, so the doc can
never fall out of sync with the code.

Usage: python scripts/gen_parameter_docs.py [--check]
"""
import dataclasses
import io
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from lightgbm_tpu.config import Config, PARAM_ALIASES  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "docs", "Parameters.md")

SECTION_TITLES = {
    "core task": "Core",
    "boosting": "Boosting / objective",
    "tree": "Tree learning",
    "dart": "DART",
    "goss": "GOSS",
    "io": "IO / dataset",
    "network": "Distributed network",
    "tpu-specific (new; no reference analog)": "TPU-specific (no reference analog)",
}

# Reference-parity parameters whose meaning isn't carried by a source
# comment.  One line each; semantics match the reference
# (docs/Parameters.rst) unless the line says otherwise.
DESC = {
    "task": "`train`, `predict`, `convert_model` or `refit` (CLI)",
    "objective": "loss to optimize: regression / regression_l1 / huber / fair / poisson / quantile / mape / gamma / tweedie / binary / multiclass / multiclassova / cross_entropy / cross_entropy_lambda / lambdarank",
    "boosting_type": "`gbdt`, `dart`, `goss` or `rf`",
    "device": "`tpu` (accelerated path; `gpu` and `cpu` alias to it with a warning)",
    "tree_learner": "`serial`, `feature`, `data` or `voting` — the four reference parallelism strategies, mapped to mesh shardings",
    "num_threads": "accepted for compatibility; host-side work uses numpy/native threads, device work is the TPU program",
    "seed": "master seed; derives the per-subsystem seeds below",
    "num_machines": "process count for multi-host training (`jax.distributed`)",
    "verbose": "<0 fatal only, 0 warnings, 1 info, >1 debug",
    "num_iterations": "boosting rounds (trees per class)",
    "learning_rate": "shrinkage applied to each tree's output",
    "num_class": "classes for multiclass objectives",
    "early_stopping_round": "stop when no validation metric improves in this many rounds (0 = off)",
    "output_freq": "evaluate/log metrics every this many iterations",
    "is_training_metric": "also evaluate metrics on the training set",
    "snapshot_freq": "save a model snapshot every this many iterations (CLI)",
    "sigmoid": "sigmoid scale for binary / cross-entropy / lambdarank",
    "boost_from_average": "initialize scores from the label average (reference boost_from_average)",
    "alpha": "huber loss delta / quantile level",
    "fair_c": "fair-loss c parameter",
    "poisson_max_delta_step": "safeguard on poisson hessians",
    "tweedie_variance_power": "tweedie variance power in [1, 2)",
    "reg_sqrt": "fit sqrt(label) and square predictions (regression)",
    "scale_pos_weight": "weight multiplier on positive class (binary)",
    "is_unbalance": "auto-reweight classes by frequency (binary)",
    "max_position": "NDCG truncation for lambdarank",
    "label_gain": "per-label relevance gains (default 2^i - 1)",
    "metric": "evaluation metric list (empty = objective's default)",
    "ndcg_eval_at": "NDCG/MAP evaluation positions",
    "num_leaves": "max leaves per tree",
    "max_depth": "max tree depth (-1 = unlimited)",
    "min_data_in_leaf": "minimum rows per leaf",
    "min_sum_hessian_in_leaf": "minimum hessian mass per leaf",
    "lambda_l1": "L1 leaf regularization",
    "lambda_l2": "L2 leaf regularization",
    "min_gain_to_split": "minimum gain for a split to be applied",
    "max_delta_step": "clamp on leaf output magnitude (0 = off)",
    "feature_fraction": "fraction of features sampled per tree",
    "feature_fraction_seed": "seed for feature sampling",
    "bagging_fraction": "fraction of rows sampled when bagging",
    "bagging_freq": "re-draw the bag every this many iterations (0 = off)",
    "bagging_seed": "seed for bagging",
    "max_bin": "max histogram bins per feature",
    "min_data_in_bin": "minimum rows per bin during mapper construction",
    "bin_construct_sample_cnt": "sample size used to fit bin mappers",
    "data_random_seed": "seed for sampling during dataset construction",
    "monotone_constraints": "per-feature monotonicity (-1/0/1)",
    "max_cat_threshold": "max categories on one side of a categorical split",
    "cat_l2": "L2 regularization in categorical split gain",
    "cat_smooth": "smoothing for categorical value ordering",
    "max_cat_to_onehot": "categories at or below this use one-vs-rest splits",
    "top_k": "votes per machine in the voting-parallel learner",
    "forcedsplits_filename": "JSON file of forced top-of-tree splits",
    "drop_rate": "fraction of trees dropped per DART iteration",
    "max_drop": "max trees dropped per iteration (-1 = unlimited)",
    "skip_drop": "probability of skipping the drop entirely",
    "xgboost_dart_mode": "xgboost-style DART normalization",
    "uniform_drop": "uniform tree-drop sampling",
    "drop_seed": "seed for DART drops",
    "top_rate": "GOSS: fraction of largest-gradient rows kept",
    "other_rate": "GOSS: fraction of remaining rows sampled",
    "data": "training data path (CLI)",
    "valid_data": "validation data path(s) (CLI)",
    "input_model": "model file to continue from / predict with",
    "output_model": "model file written after training",
    "output_result": "prediction output path",
    "convert_model": "if-else C++ output path for task=convert_model",
    "convert_model_language": "only `cpp` is supported (as in the reference)",
    "has_header": "data files carry a header row",
    "label_column": "label column (index or `name:` prefix)",
    "weight_column": "weight column",
    "group_column": "query/group column for ranking",
    "ignore_column": "columns to drop",
    "categorical_column": "columns to treat as categorical",
    "is_pre_partition": "distributed: data is already partitioned per machine",
    "use_two_round_loading": "stream the file twice instead of holding the float matrix",
    "is_save_binary_file": "save the binned dataset next to the data file",
    "is_enable_sparse": "enable sparse-aware construction",
    "enable_bundle": "exclusive feature bundling (EFB)",
    "max_conflict_rate": "max nonzero-conflict rate allowed inside a bundle",
    "is_enable_bundle": "alias field kept for config echo parity",
    "min_data_in_group": "minimum rows per categorical group",
    "use_missing": "enable missing-value handling",
    "zero_as_missing": "treat zeros as missing",
    "num_iteration_predict": "iterations used at predict time (-1 = all)",
    "is_predict_raw_score": "CLI predict: raw scores",
    "is_predict_leaf_index": "CLI predict: leaf indices",
    "is_predict_contrib": "CLI predict: SHAP contributions",
    "pred_early_stop": "margin-based early exit during prediction",
    "pred_early_stop_freq": "check the margin every this many trees",
    "pred_early_stop_margin": "margin threshold for prediction early stop",
    "local_listen_port": "rendezvous port (multi-host init)",
    "time_out": "network timeout, minutes",
    "machine_list_file": "file listing ip:port per machine",
    "machines": "comma-separated ip:port list",
    "mesh_shape": "device mesh shape for sharded training (e.g. `8` or `4,2`)",
    "mesh_axes": "mesh axis names matching mesh_shape",
    "extra": "unrecognized key=value params: warned, kept, echoed into the model file",
}


def parse_config_source():
    """(ordered) [(section, [(field, type, default, comment)])] from
    the Config dataclass source block."""
    src_path = os.path.join(REPO, "lightgbm_tpu", "config.py")
    with open(src_path) as fh:
        lines = fh.read().splitlines()
    # isolate the dataclass body
    start = next(i for i, l in enumerate(lines)
                 if l.startswith("class Config"))
    end = next(i for i in range(start, len(lines))
               if "__post_init__" in lines[i])
    sections = []
    cur_fields = []
    cur_name = "Core"
    last_field = None
    field_re = re.compile(
        r"^    (\w+): ([A-Za-z_\[\]., ]+?) = (.+?)(?:\s{2,}# (.*))?$")
    for raw in lines[start:end]:
        m = re.match(r"^    # -- (.+?) --", raw)
        if m:
            if cur_fields:
                sections.append((cur_name, cur_fields))
            cur_name = SECTION_TITLES.get(m.group(1), m.group(1))
            cur_fields = []
            last_field = None
            continue
        m = field_re.match(raw)
        if m:
            name, typ, default, comment = m.groups()
            if "dataclasses.field" in default:
                # render the factory's product, not the field() call
                live = next(f for f in dataclasses.fields(Config)
                            if f.name == name)
                default = repr(
                    live.default_factory()
                    if live.default_factory is not dataclasses.MISSING
                    else live.default)
            cur_fields.append([name, typ.strip(), default,
                               (comment or "").strip()])
            last_field = cur_fields[-1]
            continue
        m = re.match(r"^    # (.*)$", raw)
        if m and last_field is not None:
            last_field[3] = (last_field[3] + " " + m.group(1)).strip()
            continue
        if not raw.strip():
            last_field = None
    if cur_fields:
        sections.append((cur_name, cur_fields))
    return sections


def generate(sections) -> str:
    aliases = {}
    for a, canon in PARAM_ALIASES.items():
        aliases.setdefault(canon, []).append(a)
    cfg_fields = {f.name for f in dataclasses.fields(Config)}

    out = io.StringIO()
    out.write(
        "# Parameters\n\n"
        "All parameters accepted by `lightgbm_tpu` — the counterpart of "
        "the reference's `docs/Parameters.rst` (config struct: "
        "`include/LightGBM/config.h:94-306`).  Reference parameters keep "
        "their reference semantics; the final section is TPU-native "
        "surface with no reference analog.\n\n"
        "Parameters are accepted as `key=value` pairs (CLI / config "
        "file), as a `params` dict (Python / C API), or as keyword "
        "arguments on the sklearn estimators.  Aliases below map onto "
        "the canonical name exactly as in the reference alias table "
        "(`config.h:364-457`).\n\n"
        "*Generated by `scripts/gen_parameter_docs.py` from "
        "`lightgbm_tpu/config.py` — edit the source, not this file "
        "(`tests/test_docs.py` enforces sync).*\n")
    documented = set()
    for section, fields in sections:
        out.write(f"\n## {section}\n\n")
        out.write("| Parameter | Default | Aliases | Description |\n")
        out.write("|---|---|---|---|\n")
        for name, _typ, default, comment in fields:
            documented.add(name)
            # curated description wins (the inline comment is usually
            # a terser note of the same thing); source comments carry
            # the TPU-specific fields, which have no curated entry
            desc = DESC.get(name) or comment or ""
            desc = desc.replace("|", "\\|")
            al = ", ".join(f"`{a}`" for a in sorted(aliases.get(name, [])))
            dshow = default.replace("|", "\\|")
            out.write(f"| `{name}` | `{dshow}` | {al} | {desc} |\n")
    missing = cfg_fields - documented
    if missing:
        raise SystemExit(f"fields not parsed from source: {missing}")
    return out.getvalue()


def check_parsed_defaults(sections):
    """Parsed default strings must literal-eval to the live dataclass
    defaults — catches regex drift (e.g. a one-space inline comment
    folding into the captured default) that regeneration alone would
    reproduce rather than detect."""
    import ast
    live = {f.name: f for f in dataclasses.fields(Config)}
    for _section, fields in sections:
        for name, _typ, default, _comment in fields:
            f = live[name]
            if f.default is dataclasses.MISSING:   # default_factory
                continue
            try:
                parsed = ast.literal_eval(default)
            except (ValueError, SyntaxError):
                raise SystemExit(
                    f"unparseable default for {name!r}: {default!r} "
                    "(inline comment folded into the default?)")
            if parsed != f.default:
                raise SystemExit(
                    f"parsed default for {name!r} ({parsed!r}) != "
                    f"dataclass default ({f.default!r})")


def main():
    sections = parse_config_source()
    check_parsed_defaults(sections)
    text = generate(sections)
    if "--check" in sys.argv:
        try:
            with open(OUT) as fh:
                current = fh.read()
        except FileNotFoundError:
            current = None
        if current != text:
            print("docs/Parameters.md is missing or out of date — run "
                  "python scripts/gen_parameter_docs.py",
                  file=sys.stderr)
            return 1
        return 0
    with open(OUT, "w") as fh:
        fh.write(text)
    print(f"wrote {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
