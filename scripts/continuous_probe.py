"""Continuous-training probe: the closed train→evaluate→publish loop
exercised end to end, in-process and under SIGKILL.

Run by ``scripts/bench_smoke.sh`` and asserted by
``tests/test_bench_smoke.py``.  Two parts:

1. **In-process 2-cycle run** — base model published into a real
   ModelRegistry, two data slices dropped into an ingest dir, two
   continue-mode cycles: ingest → append-construct → continue-train →
   eval gate → hot publish.  Served predictions are parity-checked
   byte-identical against a direct ``Booster.predict`` of the
   published model file; then a forced live-metric regression must
   auto-roll the registry back (pointer flip, candidate quarantined).
2. **SIGKILL cycle-resume smoke** — a child lane run is SIGKILLed at
   the TRAIN phase entry through the ``continuous.cycle`` fault seam
   (``LTPU_FAULT_PLAN=continuous.cycle:2:kill`` — call 1 is ingest,
   call 2 is train), then re-run without the plan; the resumed cycle
   must publish a model byte-identical to an uninterrupted control
   run's (docs/CONTINUOUS_TRAINING.md, crash safety).

Writes ``/tmp/lgbtpu_smoke/continuous.json``.

Usage: python scripts/continuous_probe.py [out_json]
       python scripts/continuous_probe.py --child <workdir>
"""
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PARAMS = dict(objective="regression", verbose=-1, num_leaves=7,
              min_data_in_leaf=5, max_bin=31)
CYCLE_ITERS = 4


def _data(seed, n=300, shift=0.0):
    import numpy as np
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 5)
    y = X[:, 0] - 0.3 * X[:, 1] + shift
    return X, y


def _write_slice(ingest, name, seed, n=120, shift=0.0):
    import numpy as np
    X, y = _data(seed, n, shift)
    np.savetxt(os.path.join(ingest, name),
               np.column_stack([y, X]), delimiter=",")


def _setup(work):
    """Deterministic base model + lane over ``work`` (shared by the
    control / kill / resume children: identical setups fingerprint
    identically, which is what makes replay byte-identical)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.continuous import ContinuousLane
    ingest = os.path.join(work, "ingest")
    os.makedirs(ingest, exist_ok=True)
    Xb, yb = _data(0)
    base = lgb.train(PARAMS, lgb.Dataset(Xb, label=yb), 4,
                     verbose_eval=False)
    cfg = Config.from_params(dict(
        PARAMS, continuous_ingest_dir=ingest,
        continuous_iterations=CYCLE_ITERS,
        continuous_eval_holdout=0.25,
        continuous_checkpoint_freq=2))
    lane = ContinuousLane(cfg, None, name="probe", base_model=base,
                          base_data=Xb, base_label=yb,
                          train_params=dict(PARAMS))
    lane._base_model_path()
    return lane, ingest


def child(work: str) -> None:
    """One lane cycle over whatever slice/ledger state ``work`` holds
    (the kill/control/resume unit).  Prints the published model's
    bytes digest + path."""
    import hashlib
    lane, ingest = _setup(work)
    if not os.path.exists(os.path.join(ingest, "s1.csv")):
        _write_slice(ingest, "s1.csv", seed=7)
    rec = lane.run_cycle()
    model = lane._p(lane._ledger["last_good"])
    with open(model, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    print(json.dumps({"digest": digest, "accept": rec["accept"],
                      "cycle": rec["cycle"],
                      "resumed": rec.get("resumed", False)}))


def run_child(work: str, fault_plan: str = ""):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("LTPU_FAULT_PLAN", None)
    if fault_plan:
        env["LTPU_FAULT_PLAN"] = fault_plan
    run = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", work],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    info = {}
    for line in (run.stdout or "").splitlines():
        if line.strip().startswith("{"):
            info = json.loads(line)
    return run.returncode, info, run


def in_process_probe(work: str) -> dict:
    """2-cycle ingest→train→gate→publish + forced live regression →
    auto-rollback, against a REAL registry."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry
    from lightgbm_tpu.telemetry import TELEMETRY
    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    lane, ingest = _setup(work)
    registry = ModelRegistry(lane.config)
    lane.registry = registry
    registry.publish("probe", lane._p("model_base.txt"),
                     published_unix=time.time(), source="manual")

    _write_slice(ingest, "s1.csv", seed=7)
    rec1 = lane.run_cycle()
    _write_slice(ingest, "s2.csv", seed=8)
    rec2 = lane.run_cycle()

    # parity: served predictions byte-identical to a direct predict of
    # the published model file
    Xq, _ = _data(99, n=16)
    entry, served = registry.predict("probe", Xq)
    direct = lgb.Booster(
        model_file=lane._p(lane._ledger["last_good"])).predict(Xq)
    parity = bool(np.array_equal(np.asarray(served),
                                 np.asarray(direct)))
    version_before = registry.get("probe").version

    # forced regression: report a live metric far past the publish
    # bound -> rollback must fire and flip the registry pointer back
    live = (rec2["candidate_metric"] or 0.0) + 1e6
    rolled = lane.report_live_metric(live)
    version_after = registry.get("probe").version
    # rollback restores the prior version's outputs byte-identically
    _e, after = registry.predict("probe", Xq)
    prev_model = lane._p(lane._ledger["last_good"])
    rollback_parity = bool(np.array_equal(
        np.asarray(after),
        lgb.Booster(model_file=prev_model).predict(Xq)))

    c = TELEMETRY.counters()
    registry.close()
    return {
        "cycles": int(c.get("continuous_cycles", 0)),
        "rows_ingested": int(c.get("continuous_rows_ingested", 0)),
        "publishes": int(c.get("continuous_publishes", 0)),
        "rollbacks": int(c.get("continuous_rollbacks", 0)),
        "quarantined": int(c.get("continuous_quarantined", 0)),
        "cycle1_accept": bool(rec1["accept"]),
        "cycle2_accept": bool(rec2["accept"]),
        "parity": "pass" if parity else "fail",
        "rollback_fired": bool(rolled),
        "rollback_parity": "pass" if rollback_parity else "fail",
        "version_before_rollback": version_before,
        "version_after_rollback": version_after,
    }


def main() -> int:
    out_json = sys.argv[1] if len(sys.argv) > 1 \
        else "/tmp/lgbtpu_smoke/continuous.json"
    os.makedirs(os.path.dirname(out_json), exist_ok=True)
    base = os.path.join(os.path.dirname(out_json), "continuous_work")

    # part 1: in-process 2-cycle + rollback
    w1 = os.path.join(base, "inproc")
    shutil.rmtree(w1, ignore_errors=True)
    os.makedirs(w1)
    out = in_process_probe(w1)

    # part 2: SIGKILL at the train-phase entry, then resume
    wc = os.path.join(base, "control")
    wk = os.path.join(base, "kill")
    for w in (wc, wk):
        shutil.rmtree(w, ignore_errors=True)
        os.makedirs(w)
    rc_ctrl, ctrl, ctrl_run = run_child(wc)
    if rc_ctrl != 0:
        sys.stderr.write(ctrl_run.stdout + ctrl_run.stderr)
        return 1
    rc_kill, _, _ = run_child(wk,
                              fault_plan="continuous.cycle:2:kill")
    rc_res, res, res_run = run_child(wk)
    resumed = bool(res.get("resumed"))
    out.update({
        "kill_returncode": rc_kill,
        "resume_returncode": rc_res,
        "cycle_resumed_from_ledger": bool(resumed),
        "byte_identical": bool(rc_res == 0
                               and res.get("digest") == ctrl["digest"]),
        "kill_recovery": "pass" if (
            rc_kill == -9 and rc_res == 0 and resumed
            and res.get("digest") == ctrl["digest"]) else "fail",
    })
    ok = (out["parity"] == "pass" and out["rollback_parity"] == "pass"
          and out["rollback_fired"] and out["publishes"] >= 2
          and out["kill_recovery"] == "pass")
    with open(out_json, "w") as f:
        json.dump(out, f, indent=1)
    sys.stderr.write("continuous probe: " + json.dumps(out) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
