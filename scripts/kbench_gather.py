"""Microbench: row-gather bandwidth on TPU for the leaf-partition design.

Question: can we stream ONLY the frontier rows of the packed one-hot by
gathering them into a staging buffer?  The answer decides the round-4
leaf-partitioned histogram architecture.

Methodology (tpu-bench-methodology memory note): jax.block_until_ready
does NOT sync on the axon backend — sync via a tiny D2H slice; cancel
the ~65 ms dispatch overhead by differencing two loop counts.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

NPAD = 1_048_576
W = 512          # packed one-hot bytes/row at bench shape (pack=4)
L1, L2 = 20, 60


def loop_time(call, *args):
    """Per-iteration seconds via two-loop-count differencing."""
    times = {}
    for loops in (L1, L2):
        @jax.jit
        def many(*a):
            def body(i, carry):
                return call(carry, i, *a)
            return jax.lax.fori_loop(0, loops, body, jnp.int32(0))
        out = many(*args)
        _ = np.asarray(out)           # D2H sync (block_until_ready lies)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _ = np.asarray(many(*args))
            best = min(best, time.perf_counter() - t0)
        times[loops] = best
    return (times[L2] - times[L1]) / (L2 - L1)


def main():
    rng = np.random.RandomState(0)
    ohb = jnp.asarray(rng.randint(0, 127, size=(NPAD, W), dtype=np.int8))
    leaf = jnp.asarray(rng.randint(0, 256, size=NPAD, dtype=np.int32))

    # full-stream yardstick
    def g_sum(carry, i, ohb):
        return (jnp.sum(ohb, dtype=jnp.int32) + carry) & 1

    t = loop_time(g_sum, ohb)
    print(f"full stream sum {NPAD} rows: {t*1e3:.3f} ms  "
          f"read_bw={NPAD*W/t/1e9:.0f} GB/s")

    for frac in (0.5, 0.25, 0.05):
        R = int(NPAD * frac)
        idx_np = np.sort(rng.choice(NPAD, size=R, replace=False))
        idx = jnp.asarray(idx_np.astype(np.int32))

        def g_take(carry, i, ohb, idx):
            g = jnp.take(ohb, idx + (carry & 1), axis=0, mode="clip")
            return jnp.sum(g, dtype=jnp.int32) & 1

        t = loop_time(g_take, ohb, idx)
        bw = (R * W) / t / 1e9
        print(f"take+sum frac={frac} ({R} rows): {t*1e3:.3f} ms  "
              f"read_bw={bw:.0f} GB/s")

    # contiguous best case via dynamic_slice
    R = NPAD // 2

    def g_dslice(carry, i, ohb):
        g = jax.lax.dynamic_slice(ohb, (carry & 1, 0), (R, W))
        return jnp.sum(g, dtype=jnp.int32) & 1

    t = loop_time(g_dslice, ohb)
    print(f"dynamic_slice+sum {R} rows: {t*1e3:.3f} ms  "
          f"read_bw={R*W/t/1e9:.0f} GB/s")

    # compaction index build
    def g_idx(carry, i, leaf):
        m = (leaf >= carry & 1) & (leaf < 128)
        pos = jnp.cumsum(m.astype(jnp.int32)) - 1
        out = jnp.full(NPAD, NPAD - 1, jnp.int32)
        out = out.at[jnp.where(m, pos, NPAD - 1)].set(
            jnp.arange(NPAD, dtype=jnp.int32), mode="drop")
        return (out[0] + out[NPAD // 2]) & 1

    t = loop_time(g_idx, leaf)
    print(f"compaction index (cumsum+scatter): {t*1e3:.3f} ms")

    # staged: gather -> materialized buffer -> reread (sum)
    R = NPAD // 2
    idx = jnp.asarray(np.sort(rng.choice(NPAD, size=R, replace=False))
                      .astype(np.int32))

    def g_staged(carry, i, ohb, idx):
        g = jnp.take(ohb, idx + (carry & 1), axis=0, mode="clip")
        g = jax.lax.optimization_barrier(g)
        return jnp.sum(g, dtype=jnp.int32) & 1

    t = loop_time(g_staged, ohb, idx)
    print(f"staged gather {R} rows: {t*1e3:.3f} ms  "
          f"eff_bw={R*W*2/t/1e9:.0f} GB/s")

    # leaf_id row scatter (the routing writeback): update leaf at idx
    def g_scatter(carry, i, leaf, idx):
        nl = leaf.at[idx].add(carry & 1, mode="drop")
        return nl[0] & 1

    t = loop_time(g_scatter, leaf, idx)
    print(f"leaf scatter {R} rows: {t*1e3:.3f} ms")


if __name__ == "__main__":
    sys.exit(main())
