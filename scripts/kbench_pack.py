"""Microbench: streamed one-hot histogram kernels vs sub-byte packing.

Run on a real TPU chip.  Compares per-pass time of the channel-packed
streamed-one-hot kernel (and the fused route+hist kernel) at
pack = 1 / 2 / 4 against the on-the-fly quantized kernel, at the bench
shape (1M x 28 groups x 63 bins, 42-slot frontier strip).  Correctness
is asserted against the pack=1 result before timing.

Usage: python scripts/kbench_pack.py [rows]
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import (
    PACKED_STRIP, compute_group_histograms_fused,
    compute_group_histograms_pre_packed, compute_group_histograms_q_packed,
    precompute_bin_onehot, precompute_bin_onehot_packed)
from lightgbm_tpu.ops.partition import ROUTE_FIXED_COLS


def bench(fn, *args, reps=10, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    if reps == 1:
        # big-output case: don't keep two results alive at once
        out = None
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        jax.block_until_ready(out)
        return time.perf_counter() - t0, out
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kw))
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_003_520
    g, b = 28, 63
    gb = g * b
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, b, (n, g), dtype=np.uint8))
    binsT = jnp.asarray(np.asarray(bins).T)
    leaf = jnp.asarray(rng.randint(0, PACKED_STRIP, n, dtype=np.int32))
    wq = jnp.asarray(
        np.stack([rng.randint(-127, 128, n), rng.randint(0, 128, n),
                  np.ones(n)], axis=1).astype(np.int32))
    scales = jnp.ones(3, jnp.float32)
    slots = jnp.arange(PACKED_STRIP, dtype=jnp.int32)

    t, ohb1 = bench(precompute_bin_onehot, bins, max_group_bin=b, reps=1)
    print(f"precompute pack=1: {t*1e3:.1f} ms  {ohb1.nbytes/2**20:.0f} MB")
    packs = {1: ohb1}
    for pk in (2, 4):
        if gb % pk:
            continue
        t, o = bench(precompute_bin_onehot_packed, bins, max_group_bin=b,
                     pack=pk, reps=1)
        print(f"precompute pack={pk}: {t*1e3:.1f} ms "
              f"{o.nbytes/2**20:.0f} MB")
        packs[pk] = o

    # per-call walls on the remote-attached chip carry ~60-100 ms of
    # dispatch overhead; real training amortizes it inside one jitted
    # while_loop, so each kernel is timed as 20 passes inside ONE jit
    # (slots rolled per iteration to defeat loop-hoisting/CSE)
    LOOPS = 20

    import functools as ft

    def loop_time(call, *args):
        # each iteration's slots depend on the previous histogram so the
        # loop body cannot be overlapped/elided (matches training, where
        # round i+1's frontier depends on round i's splits)
        @jax.jit
        def many(*a):
            def body(i, carry):
                acc, s = carry
                h = call(s, *a)
                v = h[0, 0, 0, 0]
                bump = jnp.where(jnp.isfinite(v), 0, 1).astype(jnp.int32)
                return acc + v, jnp.roll(slots + bump, i)
            out, _ = jax.lax.fori_loop(0, LOOPS, body,
                                       (jnp.float32(0.0), slots))
            return out
        jax.block_until_ready(many(*args))
        t0 = time.perf_counter()
        jax.block_until_ready(many(*args))
        return (time.perf_counter() - t0) / LOOPS

    ref = None
    print("\n-- pre_packed (streamed, strips=1, quant) --")
    for pk, ohb in packs.items():
        h = compute_group_histograms_pre_packed(
            ohb, wq, scales, leaf, slots, max_group_bin=b, block=2048,
            strips=1, quant=True, pack=pk, num_groups=g)
        if ref is None:
            ref = np.asarray(h)
        else:
            err = np.abs(np.asarray(h) - ref).max()
            assert err == 0.0, f"pack={pk} mismatch {err}"
        t = loop_time(
            lambda s, o, pk=pk: compute_group_histograms_pre_packed(
                o, wq, scales, leaf, s, max_group_bin=b, block=2048,
                strips=1, quant=True, pack=pk, num_groups=g), ohb)
        print(f"pack={pk}: {t*1e3:.2f} ms/pass")

    print("\n-- q_packed (on-the-fly rebuild, quant) --")
    h = compute_group_histograms_q_packed(bins, wq, scales, leaf, slots,
                                          max_group_bin=b, block=2048,
                                          strips=1)
    err = np.abs(np.asarray(h) - ref).max()
    assert err == 0.0, f"otf mismatch {err}"
    t = loop_time(lambda s, bn: compute_group_histograms_q_packed(
        bn, wq, scales, leaf, s, max_group_bin=b, block=2048, strips=1),
        bins)
    print(f"otf: {t*1e3:.2f} ms/pass")

    print("\n-- fused route+hist (strips=1, quant) --")
    nb = 15 + (b + 7) // 8
    route = jnp.zeros((255, nb), jnp.float32)  # inactive: route no-op
    wT = jnp.asarray(np.asarray(wq).T)
    ref_f = None
    for pk, ohb in packs.items():
        h, lf = compute_group_histograms_fused(
            ohb, binsT, wT, scales, leaf, route, slots, max_group_bin=b,
            block=2048, strips=1, quant=True, pack=pk, num_groups=g)
        if ref_f is None:
            ref_f = np.asarray(h)
            assert np.array_equal(np.asarray(lf), np.asarray(leaf))
        else:
            err = np.abs(np.asarray(h) - ref_f).max()
            assert err == 0.0, f"fused pack={pk} mismatch {err}"
        t = loop_time(
            lambda s, o, pk=pk: compute_group_histograms_fused(
                o, binsT, wT, scales, leaf, route, s, max_group_bin=b,
                block=2048, strips=1, quant=True, pack=pk,
                num_groups=g)[0], ohb)
        print(f"pack={pk}: {t*1e3:.2f} ms/pass")
    err = np.abs(ref_f - ref).max()
    assert err == 0.0, f"fused vs pre mismatch {err}"
    print("\nall correctness checks passed")


if __name__ == "__main__":
    main()
