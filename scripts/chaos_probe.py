"""Chaos probe: seeded randomized multi-fault survival across the
train / serve / continuous stacks, gated by the invariant registry.

Run by ``scripts/bench_smoke.sh`` and asserted by
``tests/test_bench_smoke.py``.  For each seed in ``CHAOS_SEEDS``
(default 4) it runs one chaos plan per workload (>= 20 plans at the
default budget; the transport workload contributes two — a network
sweep and a coordinator-kill), every plan drawn by the deterministic
chaos
scheduler (``reliability/chaos.py``) so ANY red run replays exactly
from the seed it prints:

- **train** (subprocess): two faults drawn over the ``gbdt.*`` +
  ``checkpoint.io`` seams — kills, OOMs, transient errors, hangs
  (bounded by the dispatch/checkpoint watchdogs), slowdowns — then
  the same command reruns clean and must auto-resume to a model
  BYTE-IDENTICAL to an uninterrupted reference, with no orphaned
  partial artifacts and (whenever work was lost) a nonzero exit plus
  a flight dump naming the seam.
- **serve** (in-process): two faults over ``predict.dispatch``
  (no kills — the probe must survive its own workload); every
  successful response must be byte-identical to a direct
  ``Booster.predict``, every failure must surface loudly, hangs are
  cut by ``watchdog_serve_s``.
- **continuous** (in-process): two faults over ``continuous.cycle``;
  the lane retries from its ledger until the cycle lands, and the
  candidate must be byte-identical to a fault-free reference lane
  over the same slices, with the ledger still replayable.
- **transport** (in-process, threaded TCP world): per seed, (a) a
  2-rank world runs exact-integer allreduces under two faults drawn
  from the NETWORK action pool (``corrupt`` / ``partition:<ms>`` /
  ``dup`` / ``slow`` / ``peer_slow``) on ``transport.round`` — the
  CRC must catch every corrupt frame, the in-epoch reconnect must
  heal every partition with zero degradation, and every completed
  result must be BIT-identical to the fault-free expectation
  (``transport_no_silent_misdata`` + ``partition_heals``); and (b) a
  3-rank world loses its coordinator mid-run — the lowest surviving
  rank must take over (``coordinator_change`` journaled), the world
  reforms, and the remaining rounds stay bit-exact
  (``coordinator_failover``).

Env knobs: ``CHAOS_SEEDS`` (how many seeds per workload),
``CHAOS_BUDGET_S`` (wall budget — on excess the sweep stops with a
note instead of blowing the smoke wall; a nightly job widens both
without touching tier-1).

Usage: python scripts/chaos_probe.py [out_json]
       python scripts/chaos_probe.py --child <model_out>
"""
import glob
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEEDS = int(os.environ.get("CHAOS_SEEDS", "4"))
BUDGET_S = float(os.environ.get("CHAOS_BUDGET_S", "420"))
TRAIN_ITERS = 8


# ---------------------------------------------------------------------------
# train workload child (subprocess — kills must take only the child)
# ---------------------------------------------------------------------------
def child(out_model: str) -> None:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.telemetry import TELEMETRY
    TELEMETRY.configure("counters")
    rng = np.random.RandomState(13)
    X = rng.randn(500, 7)
    y = (X[:, 0] + 0.3 * rng.randn(500) > 0).astype(float)
    params = dict(
        objective="binary", num_leaves=15, max_bin=63, verbose=1,
        dispatch_chunk=2, checkpoint_freq=2, output_model=out_model,
        retry_backoff_s=0.0, dispatch_retries=0,
        # the dispatch deadline must clear a COLD XLA compile (the
        # first enqueue traces + compiles the fused chunk) while
        # staying under the drawn hang durations (8-15 s below)
        watchdog_dispatch_s=6.0, watchdog_checkpoint_s=2.0,
        flight_recorder_out=os.path.join(
            os.path.dirname(out_model), "flight"))
    bst = lgb.train(params, lgb.Dataset(X, label=y), TRAIN_ITERS,
                    verbose_eval=False)
    bst.save_model(out_model)


def run_child(out_model: str, fault_plan: str = ""):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("LTPU_FAULT_PLAN", None)
    if fault_plan:
        env["LTPU_FAULT_PLAN"] = fault_plan
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         out_model],
        env=env, cwd=REPO, capture_output=True, text=True,
        timeout=300).returncode


def train_plan(seed: int, workroot: str, ref_model: str) -> dict:
    from lightgbm_tpu.reliability.chaos import chaos_spec
    from lightgbm_tpu.reliability.invariants import (ChaosContext,
                                                     violations)
    spec = chaos_spec(seed, 2, "gbdt.*,checkpoint.io",
                      hang_ms=(8000, 15000), slow_ms=(5, 30))
    wd = os.path.join(workroot, f"train_seed{seed}")
    shutil.rmtree(wd, ignore_errors=True)
    os.makedirs(wd)
    model = os.path.join(wd, "model.txt")
    rc1 = run_child(model, fault_plan=spec)
    rc2 = run_child(model)                      # clean rerun: resume
    ctx = ChaosContext(
        workdir=wd, reference_model=ref_model, final_model=model,
        exit_code=rc1, work_lost=(rc1 != 0),
        flight_dumps=glob.glob(os.path.join(wd, "flight-*.flight.json")),
        seed=seed, plan=spec)
    viol = violations(ctx, ["resume_byte_identical",
                            "no_partial_artifacts", "loud_failure"])
    if rc2 != 0:
        viol.append(f"[seed {seed}] clean rerun exited {rc2} — "
                    "resume did not recover")
    return {"workload": "train", "seed": seed, "plan": spec,
            "fault_rc": rc1, "resume_rc": rc2,
            "violations": viol, "green": not viol}


# ---------------------------------------------------------------------------
# serve workload (in-process; action set excludes kill)
# ---------------------------------------------------------------------------
def serve_plan(seed: int, setup: dict) -> dict:
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.reliability.chaos import chaos_spec
    from lightgbm_tpu.reliability.faults import FAULTS
    from lightgbm_tpu.reliability.invariants import (ChaosContext,
                                                     violations)
    from lightgbm_tpu.serving import ModelRegistry
    bst, X, expected = setup["bst"], setup["X"], setup["expected"]
    spec = chaos_spec(
        seed, 2, "predict.dispatch",
        actions=("oom", "ConnectionError", "OSError", "TimeoutError",
                 "hang", "slow"),
        max_nth=6, hang_ms=(1500, 2500), slow_ms=(2, 15))
    cfg = Config.from_params({
        "verbose": -1, "watchdog_serve_s": 0.5,
        "serve_batch_deadline_ms": 0.0, "dispatch_retries": 0,
        "retry_backoff_s": 0.0})
    registry = ModelRegistry(cfg)
    registry.publish("chaos", bst, warm=(),
                     predict_kwargs={"device": True})
    FAULTS.configure(spec)
    served, matched, failures = [], [], []
    try:
        for k in range(10):
            rows = X[k * 6:(k + 1) * 6]
            try:
                _entry, out = registry.predict("chaos", rows)
            except Exception as e:  # noqa: BLE001 - loud by design
                failures.append(f"req{k}:{type(e).__name__}")
                continue
            served.append(np.asarray(out))
            matched.append(expected[k * 6:(k + 1) * 6])
    finally:
        FAULTS.reset()
        registry.close()
    ctx = ChaosContext(
        served=np.concatenate(served) if served else None,
        expected=np.concatenate(matched) if matched else None,
        seed=seed, plan=spec)
    viol = violations(ctx, ["serving_parity"])
    if not served:
        viol.append(f"[seed {seed}] every request failed — the "
                    "serving plane did not survive the plan")
    return {"workload": "serve", "seed": seed, "plan": spec,
            "requests_ok": len(served) * 6, "failures": failures,
            "violations": viol, "green": not viol}


# ---------------------------------------------------------------------------
# continuous workload (in-process; ledger replay until the cycle lands)
# ---------------------------------------------------------------------------
def continuous_setup(workroot: str) -> dict:
    import numpy as np

    import lightgbm_tpu as lgb
    rng = np.random.RandomState(5)
    X0 = rng.randn(350, 5)
    y0 = X0[:, 0] - 0.25 * X0[:, 1]
    params = {"objective": "regression", "verbose": -1,
              "num_leaves": 7, "min_data_in_leaf": 5, "max_bin": 31}
    bst = lgb.train(params, lgb.Dataset(X0, label=y0), 4,
                    verbose_eval=False)
    base_path = os.path.join(workroot, "cont_base.txt")
    bst.save_model(base_path)
    slices = []
    for i, sd in enumerate((21, 22)):
        r2 = np.random.RandomState(sd)
        Xs = r2.randn(100, 5)
        ys = Xs[:, 0] - 0.25 * Xs[:, 1]
        slices.append((f"s{i}.csv",
                       np.column_stack([ys, Xs])))
    return {"X0": X0, "y0": y0, "params": params,
            "base_path": base_path, "slices": slices}


def _run_lane(state_dir: str, ingest_dir: str, setup: dict,
              fault_spec: str = "", max_attempts: int = 8):
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.continuous import ContinuousLane
    from lightgbm_tpu.reliability.faults import FAULTS
    os.makedirs(ingest_dir, exist_ok=True)
    for name, arr in setup["slices"]:
        np.savetxt(os.path.join(ingest_dir, name), arr,
                   delimiter=",")
    cfg = Config.from_params(dict(
        setup["params"], continuous_ingest_dir=ingest_dir,
        continuous_state_dir=state_dir, continuous_iterations=3,
        continuous_eval_holdout=0.25, watchdog_continuous_s=10.0))
    lane = ContinuousLane(cfg, None, name="chaos",
                          base_model=setup["base_path"],
                          base_data=setup["X0"],
                          base_label=setup["y0"],
                          train_params=dict(setup["params"]))
    lane._base_model_path()
    if fault_spec:
        FAULTS.configure(fault_spec)
    attempts, done, errors = 0, None, []
    try:
        while attempts < max_attempts and done is None:
            attempts += 1
            try:
                done = lane.run_cycle()
            except Exception as e:  # noqa: BLE001 - ledger replays
                errors.append(type(e).__name__)
    finally:
        FAULTS.reset()
    return done, attempts, errors


def continuous_plan(seed: int, workroot: str, setup: dict,
                    ref_model: str) -> dict:
    from lightgbm_tpu.reliability.chaos import chaos_spec
    from lightgbm_tpu.reliability.invariants import (ChaosContext,
                                                     violations)
    spec = chaos_spec(
        seed, 2, "continuous.cycle",
        actions=("oom", "ConnectionError", "OSError", "RuntimeError",
                 "hang", "slow"),
        max_nth=4, hang_ms=(200, 500), slow_ms=(2, 15))
    sdir = os.path.join(workroot, f"cont_seed{seed}")
    idir = os.path.join(sdir, "ingest")
    shutil.rmtree(sdir, ignore_errors=True)
    os.makedirs(sdir)
    done, attempts, errors = _run_lane(sdir, idir, setup,
                                       fault_spec=spec)
    ctx = ChaosContext(
        workdir=sdir, ledger_path=os.path.join(sdir, "ledger.json"),
        reference_model=ref_model,
        final_model=os.path.join(sdir, "model_cycle_1.txt"),
        seed=seed, plan=spec)
    viol = violations(ctx, ["resume_byte_identical",
                            "no_partial_artifacts",
                            "ledger_converges"])
    if done is None:
        viol.append(f"[seed {seed}] cycle never completed in "
                    f"{attempts} ledger replays ({errors})")
    return {"workload": "continuous", "seed": seed, "plan": spec,
            "attempts": attempts, "cycle_errors": errors,
            "violations": viol, "green": not viol}


# ---------------------------------------------------------------------------
# transport workload (in-process threaded TCP world; network chaos)
# ---------------------------------------------------------------------------
# the survivable network pool: no kill/oom/peer_drop — an in-process
# probe must outlive its own faults, and these five are exactly the
# shapes the hardened transport claims to absorb
TRANSPORT_POOL = ("corrupt", "partition", "dup", "slow", "peer_slow")
TRANSPORT_ROUNDS = 6


def _transport_world(world, fn, config=None, timeout=30.0):
    """Threaded ``world``-rank TCP transport; returns (results,
    errors) per rank.  Mirrors tests/test_transport.py::_run_world
    but never re-raises — the caller feeds errors to the invariants."""
    import socket as _socket
    import threading

    from lightgbm_tpu.parallel import transport as T
    s = _socket.socket()
    s.bind(("localhost", 0))
    coord = f"localhost:{s.getsockname()[1]}"
    s.close()
    results, errors, tps = ([None] * world for _ in range(3))

    def _member(rank):
        try:
            tps[rank] = T.TcpTransport.create(coord, world, rank,
                                              config=config)
            results[rank] = fn(tps[rank], rank)
        except BaseException as e:  # noqa: BLE001 - judged by invariants
            errors[rank] = e
        finally:
            if tps[rank] is not None:
                tps[rank].close()

    threads = [threading.Thread(target=_member, args=(r,),
                                daemon=True) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    for i, t in enumerate(threads):
        if t.is_alive() and errors[i] is None:
            errors[i] = TimeoutError(f"rank {i} hung past {timeout}s")
    return results, errors


def _journal_kinds(since_seq: int):
    from lightgbm_tpu.telemetry import TELEMETRY
    return [e["kind"] for e in TELEMETRY.journal.events()
            if e["seq"] > since_seq]


def _counter_delta(before: dict, keys):
    from lightgbm_tpu.telemetry import TELEMETRY
    after = TELEMETRY.counters()
    return {k: after.get(k, 0) - before.get(k, 0) for k in keys}


_TCP_KEYS = ("collective_tcp_crc_errors", "collective_tcp_reconnects",
             "collective_tcp_dup_frames", "collective_tcp_rehomes",
             "collective_tcp_coordinator_changes")


def transport_plan(seed: int) -> dict:
    """(a) 2-rank network-chaos run: corrupt/partition/dup/slow drawn
    on ``transport.round``, results bit-compared to the fault-free
    expectation."""
    import numpy as np

    from lightgbm_tpu.reliability import watchdog
    from lightgbm_tpu.reliability.chaos import chaos_spec
    from lightgbm_tpu.reliability.faults import FAULTS
    from lightgbm_tpu.reliability.invariants import (ChaosContext,
                                                     violations)
    from lightgbm_tpu.telemetry import TELEMETRY
    spec = chaos_spec(seed, 2, "transport.round",
                      actions=TRANSPORT_POOL, max_nth=8,
                      slow_ms=(2, 15), partition_ms=(20, 80))
    actions = {e.split(":")[2] for e in spec.split(";")}
    before = TELEMETRY.counters()
    seq0 = max([e["seq"] for e in TELEMETRY.journal.events()],
               default=0)
    FAULTS.configure(spec)
    watchdog.set_deadline("collective", 8.0)

    def work(tp, r):
        return [tp.allreduce_sum(
            np.arange(8, dtype=np.int64) * (k + 1) + r)
            for k in range(TRANSPORT_ROUNDS)]

    try:
        res, errs = _transport_world(2, work)
    finally:
        FAULTS.reset()
        watchdog.set_deadline("collective", 0.0)
    failed = any(e is not None for e in errs)
    expected = [np.arange(8, dtype=np.int64) * (k + 1) * 2 + 1
                for k in range(TRANSPORT_ROUNDS)]
    flat = [a for r in res if r is not None for a in r]
    ctx = ChaosContext(
        seed=seed, plan=spec,
        transport_result=None if failed else flat,
        transport_expected=None if failed
        else [e for r in res if r is not None for e in expected],
        transport_counters=_counter_delta(before, _TCP_KEYS),
        transport_events=_journal_kinds(seq0),
        transport_corrupt_fired="corrupt" in actions,
        transport_partition_fired="partition" in actions,
        transport_failed=failed)
    viol = violations(ctx, ["transport_no_silent_misdata",
                            "partition_heals"])
    return {"workload": "transport", "mode": "net", "seed": seed,
            "plan": spec, "errors": [type(e).__name__
                                     for e in errs if e is not None],
            "counters": ctx.transport_counters,
            "violations": viol, "green": not viol}


def transport_failover_plan(seed: int) -> dict:
    """(b) coordinator-kill run: a 3-rank world loses rank 0 (the
    coordinator) after round 2; the survivors must fail over to rank 1
    and finish the remaining rounds bit-exact over the reformed
    world."""
    import numpy as np

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.parallel.transport import TransportPeerLost
    from lightgbm_tpu.reliability import watchdog
    from lightgbm_tpu.reliability.invariants import (ChaosContext,
                                                     violations)
    from lightgbm_tpu.telemetry import TELEMETRY
    kill_at = 3
    before = TELEMETRY.counters()
    seq0 = max([e["seq"] for e in TELEMETRY.journal.events()],
               default=0)
    cfg = Config.from_params({"verbose": -1,
                              "transport_reconnect_retries": 1})
    watchdog.set_deadline("collective", 2.0)
    worlds = {}

    def work(tp, r):
        outs = []
        k = 0
        while k < TRANSPORT_ROUNDS:
            if r == 0 and k == kill_at:
                return outs          # coordinator dies (abrupt close)
            try:
                outs.append(tp.allreduce_sum(
                    np.arange(8, dtype=np.int64) * (k + 1) + r))
                tp.epoch_tick(handoff=lambda: b"",
                              allow_degraded=True)
            except (TransportPeerLost, watchdog.StallError):
                # the dead coordinator surfaces here: reform the
                # world (failover inside), then redo the round
                tp.epoch_tick(handoff=lambda: b"",
                              allow_degraded=True)
                continue
            k += 1
        worlds[r] = tp.world_size
        return outs

    try:
        res, errs = _transport_world(3, work, config=cfg, timeout=40.0)
    finally:
        watchdog.set_deadline("collective", 0.0)
    failed = any(e is not None for e in errs)

    def expect(r):
        # rounds before the kill sum all three ranks; after the
        # failover the world is {1, 2}
        return [np.arange(8, dtype=np.int64) * (k + 1) * 3 + 3
                if k < kill_at else
                np.arange(8, dtype=np.int64) * (k + 1) * 2 + 3
                for k in range(TRANSPORT_ROUNDS)]

    flat, flat_exp = [], []
    if not failed:
        for r in (1, 2):
            flat.extend(res[r] or [])
            flat_exp.extend(expect(r))
    ctx = ChaosContext(
        seed=seed, plan=f"coordinator-kill@round{kill_at}",
        transport_result=None if failed else flat,
        transport_expected=None if failed else flat_exp,
        transport_counters=_counter_delta(before, _TCP_KEYS),
        transport_events=_journal_kinds(seq0),
        coordinator_killed=True, transport_failed=failed,
        transport_world_start=3,
        transport_world_end=worlds.get(1))
    viol = violations(ctx, ["coordinator_failover"])
    if not failed and worlds.get(1) != 2:
        viol.append(f"[seed {seed}] survivors ended at world "
                    f"{worlds.get(1)}, expected 2")
    return {"workload": "transport", "mode": "failover", "seed": seed,
            "plan": ctx.plan,
            "errors": [type(e).__name__ for e in errs
                       if e is not None],
            "counters": ctx.transport_counters,
            "violations": viol, "green": not viol}


# ---------------------------------------------------------------------------
def main() -> int:
    out_json = sys.argv[1] if len(sys.argv) > 1 \
        else "/tmp/lgbtpu_smoke/chaos.json"
    os.makedirs(os.path.dirname(out_json), exist_ok=True)
    workroot = os.path.join(os.path.dirname(out_json), "chaos_work")
    shutil.rmtree(workroot, ignore_errors=True)
    os.makedirs(workroot)
    t0 = time.perf_counter()

    from lightgbm_tpu.telemetry import TELEMETRY
    TELEMETRY.configure("counters")
    TELEMETRY.flight.arm(os.path.join(workroot, "probe_flight"))

    # fault-free references, built once and shared by every seed
    ref_dir = os.path.join(workroot, "train_ref")
    os.makedirs(ref_dir)
    ref_model = os.path.join(ref_dir, "model.txt")
    rc = run_child(ref_model)
    if rc != 0:
        sys.stderr.write("chaos probe: reference train child failed\n")
        return 1
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    Xs = rng.randn(300, 5)
    ys = (Xs[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 7, "min_data_in_leaf": 5},
                    lgb.Dataset(Xs, label=ys), 5, verbose_eval=False)
    bst.predict(Xs[:6], device=True)   # warm the 16-row bucket the
    # 6-row chaos requests land on: cold compiles must not masquerade
    # as stalls under watchdog_serve_s
    serve_setup = {"bst": bst, "X": Xs,
                   "expected": np.asarray(
                       bst.predict(Xs[:60], device=True))}
    cont_setup = continuous_setup(workroot)
    cont_ref_dir = os.path.join(workroot, "cont_ref")
    done, _, _ = _run_lane(cont_ref_dir,
                           os.path.join(cont_ref_dir, "ingest"),
                           cont_setup)
    if done is None:
        sys.stderr.write("chaos probe: reference lane cycle failed\n")
        return 1
    cont_ref_model = os.path.join(cont_ref_dir, "model_cycle_1.txt")

    plans, budget_exceeded = [], False
    for seed in range(1, SEEDS + 1):
        for run in (lambda: train_plan(seed, workroot, ref_model),
                    lambda: serve_plan(seed, serve_setup),
                    lambda: continuous_plan(seed, workroot,
                                            cont_setup,
                                            cont_ref_model),
                    lambda: transport_plan(seed),
                    lambda: transport_failover_plan(seed)):
            if time.perf_counter() - t0 > BUDGET_S:
                budget_exceeded = True
                break
            plans.append(run())
            p = plans[-1]
            sys.stderr.write(
                f"chaos[{p['workload']} seed={p['seed']}] "
                f"{'green' if p['green'] else 'RED'} plan={p['plan']}"
                + (f" violations={p['violations']}"
                   if p["violations"] else "") + "\n")
        if budget_exceeded:
            break

    counters = TELEMETRY.counters()
    green = sum(1 for p in plans if p["green"])
    out = {
        "seeds": SEEDS,
        "budget_s": BUDGET_S,
        "elapsed_s": round(time.perf_counter() - t0, 3),
        "budget_exceeded": budget_exceeded,
        "plans_run": len(plans),
        "plans_green": green,
        "invariants": ["resume_byte_identical", "no_partial_artifacts",
                       "ledger_converges", "serving_parity",
                       "loud_failure", "transport_no_silent_misdata",
                       "partition_heals", "coordinator_failover"],
        "stalls_total": int(counters.get("stalls_total", 0)),
        "faults_injected": int(counters.get("faults_injected", 0)),
        "plans": plans,
        "status": "pass" if green == len(plans) and plans else "fail",
    }
    with open(out_json, "w") as f:
        json.dump(out, f, indent=1)
    sys.stderr.write(
        f"chaos probe: {green}/{len(plans)} plans green in "
        f"{out['elapsed_s']}s (budget {BUDGET_S:g}s"
        + (", EXCEEDED — sweep truncated" if budget_exceeded else "")
        + f"); faults_injected={out['faults_injected']}\n")
    return 0 if out["status"] == "pass" else 1


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        child(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
