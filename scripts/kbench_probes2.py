"""Round-4 probes, part 2: the two Mosaic capabilities the cheap
partition kernel needs.

jax 0.9 status (re-run after the image's 0.8.x -> 0.9.0 upgrade):
P5 still unsupported (same gather shape-check / compiler crash).
P6 REGRESSED — the dynamic-offset VMEM->HBM async copy that worked
under 0.8.x now crashes the 0.9 Mosaic compiler (remote_compile 500);
only the unwired partition prototype used it.  P7 works with the
masked-row store spelling below (0.9 rejects scalar stores to VMEM).

P5  dynamic LANE gather in VMEM: out[:, d] = x[:, idx[d]] — compaction
    by index gather (15x less MXU than a permutation matmul).  Tried
    as jnp.take / take_along_axis / x[:, idx] spellings.
P6  async_copy VMEM -> HBM at a DYNAMIC (128-aligned) column offset
    (the pending-buffer flush; the part-1 P3 probe crashed as an
    HBM->HBM copy).
P7  SMEM scalar carry across sequential grid steps (running cursors).
"""
import sys
import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def sync(x):
    return np.asarray(x)


def probe_lane_gather():
    R, C = 48, 512
    rng = np.random.RandomState(0)
    x = rng.randint(-100, 100, (R, C)).astype(np.int8)
    idx = rng.randint(0, C, C).astype(np.int32)

    spellings = {
        "jnp.take axis=1": lambda xv, iv: jnp.take(xv, iv, axis=1),
        "take_along_axis": lambda xv, iv: jnp.take_along_axis(
            xv, jnp.broadcast_to(iv[None, :], xv.shape), axis=1),
    }
    ok_any = False
    for name, fn in spellings.items():
        def body(x_ref, i_ref, o_ref, fn=fn):
            o_ref[:] = fn(x_ref[:], i_ref[0, :])
        try:
            out = pl.pallas_call(
                body,
                in_specs=[pl.BlockSpec((R, C), lambda: (0, 0)),
                          pl.BlockSpec((1, C), lambda: (0, 0))],
                out_specs=pl.BlockSpec((R, C), lambda: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((R, C), jnp.int8),
            )(jnp.asarray(x), jnp.asarray(idx)[None, :])
            got = sync(out)
            ok = (got == x[:, idx]).all()
            print(f"P5 lane gather [{name}]: {'OK' if ok else 'WRONG'}")
            ok_any = ok_any or ok
        except Exception as e:
            print(f"P5 lane gather [{name}]: FAIL ({type(e).__name__}: "
                  f"{str(e)[:160]})")
    return ok_any


def probe_vmem_to_hbm_dyn():
    R, NCAP, C = 48, 8192, 512

    def body(off_ref, x_ref, out_ref, scratch, sem):
        scratch[:] = x_ref[:] + 1
        off = off_ref[0]
        cp = pltpu.make_async_copy(scratch, out_ref.at[:, pl.ds(off, C)],
                                  sem)
        cp.start()
        cp.wait()

    x = jnp.ones((R, C), jnp.int8)
    off = jnp.asarray([1280], jnp.int32)  # 128-aligned, not C-aligned
    try:
        out = pl.pallas_call(
            body,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec((R, C), lambda i: (0, 0))],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            out_shape=jax.ShapeDtypeStruct((R, NCAP), jnp.int8),
            scratch_shapes=[pltpu.VMEM((R, C), jnp.int8),
                            pltpu.SemaphoreType.DMA],
        )(off, x)
        got = sync(out)
        ok = (got[:, 1280:1280 + C] == 2).all()
        print(f"P6 VMEM->HBM dyn-offset copy: {'OK' if ok else 'WRONG'}")
        return bool(ok)
    except Exception as e:
        print(f"P6 VMEM->HBM dyn-offset copy: FAIL ({type(e).__name__}: "
              f"{str(e)[:200]})")
        return False


def probe_smem_carry():
    def body(x_ref, out_ref, cnt):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            cnt[0] = 0
            out_ref[:] = jnp.zeros_like(out_ref)

        k = jnp.sum(x_ref[:].astype(jnp.int32))
        # jax 0.9 Mosaic rejects scalar stores to VMEM
        # ("Cannot store scalars to VMEM"); a masked full-row store
        # expresses the same per-step write and lowers fine
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 8), 1)
        out_ref[:] = jnp.where(lane == i, cnt[0], out_ref[:])
        cnt[0] = cnt[0] + k

    x = jnp.ones((8, 8, 128), jnp.int8)
    try:
        out = pl.pallas_call(
            body,
            grid=(8,),
            in_specs=[pl.BlockSpec((1, 8, 128), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, 8), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 8), jnp.int32),
            scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        )(x)
        got = sync(out)[0]
        want = np.arange(8) * 1024
        ok = (got == want).all()
        print(f"P7 SMEM carry across steps: {'OK' if ok else 'WRONG'} "
              f"({got.tolist()})")
        return bool(ok)
    except Exception as e:
        print(f"P7 SMEM carry: FAIL ({type(e).__name__}: {str(e)[:160]})")
        return False


if __name__ == "__main__":
    r = [probe_lane_gather(), probe_vmem_to_hbm_dyn(), probe_smem_carry()]
    sys.exit(0 if all(r) else 1)
