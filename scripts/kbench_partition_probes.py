"""Feasibility probes for the leaf-partitioned layout (round 4).

Validates, on the real chip, the Mosaic capabilities the partition
design rests on:
  P1  dynamic-sublane accumulate:  out_ref[pl.ds(off, 8), :] += x
  P2  masked grid with repeated index_map entries — per-step cost of
      skipped steps (same block index => no DMA refetch)
  P3  manual async_copy VMEM->HBM at a DYNAMIC 128-aligned column
      offset of a transposed (R, Ncap) int8 ref
  P4  in-kernel lane cumsum + one-hot permutation matmul (compaction)
Each probe prints OK/FAIL + a rough time so the design can pick block
sizes.  D2H-sync timing (block_until_ready lies on axon).
"""
import functools
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def sync(x):
    return np.asarray(x)


# ----------------------------------------------------------------- P1
def probe_dyn_sublane():
    C = 256

    def body(off_ref, x_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        off = off_ref[0]
        out_ref[pl.ds(off, 8), :] += x_ref[:]

    x = jnp.ones((8, 128), jnp.int32)
    off = jnp.asarray([24], jnp.int32)
    try:
        out = pl.pallas_call(
            body,
            grid=(4,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.int32),
        )(off, x)
        got = sync(out)
        ok = (got[24:32] == 4).all() and got[:24].sum() == 0 \
            and got[32:].sum() == 0
        print(f"P1 dynamic-sublane accumulate: {'OK' if ok else 'WRONG'}")
        return ok
    except Exception as e:
        print(f"P1 dynamic-sublane accumulate: FAIL ({type(e).__name__}: "
              f"{str(e)[:200]})")
        return False


# ----------------------------------------------------------------- P2
def probe_masked_grid():
    C = 1024
    N = 1_048_576
    nblocks = N // C

    def body(nreal_ref, idx_ref, x_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        @pl.when(i < nreal_ref[0])
        def _():
            out_ref[:] += jnp.sum(x_ref[:].astype(jnp.int32))

    x = jnp.ones((N // 128, 128), jnp.int8)

    def run(nreal, idx_np):
        idx = jnp.asarray(idx_np, jnp.int32)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(nblocks,),
            in_specs=[pl.BlockSpec((C // 128, 128),
                                   lambda i, nreal, idx: (idx[i], 0))],
            out_specs=pl.BlockSpec((1, 1), lambda i, nreal, idx: (0, 0)),
        )
        f = pl.pallas_call(
            body, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32))

        @jax.jit
        def many(x, nreal, idx):
            def step(k, acc):
                return acc + f(nreal + k * 0, idx, x)[0, 0]
            return jax.lax.fori_loop(0, 30, step, jnp.int32(0))

        nreal_a = jnp.asarray([nreal], jnp.int32)
        r = sync(many(x, nreal_a, idx))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            sync(many(x, nreal_a, idx))
            best = min(best, time.perf_counter() - t0)
        return best / 30, r

    # all real
    idx_full = np.arange(nblocks)
    t_full, r = run(nblocks, idx_full)
    ok = r == 30 * N
    # 1/16 real, tail repeats last real block
    nreal = nblocks // 16
    idx_sparse = np.concatenate(
        [np.arange(nreal), np.full(nblocks - nreal, nreal - 1)])
    t_sparse, r2 = run(nreal, idx_sparse)
    ok = ok and r2 == 30 * nreal * C
    per_skip = (t_sparse - t_full * nreal / nblocks) / (nblocks - nreal)
    print(f"P2 masked grid: {'OK' if ok else 'WRONG'} full={t_full*1e3:.3f} "
          f"ms, 1/16={t_sparse*1e3:.3f} ms, ~{per_skip*1e9:.0f} ns/skipped "
          f"step ({nblocks} blocks of {C})")
    return ok


# ----------------------------------------------------------------- P3
def probe_dyn_copy():
    R, NCAP, C = 32, 8192, 512

    def body(off_ref, x_ref, out_ref, sem):
        i = pl.program_id(0)
        off = off_ref[0]
        cp = pltpu.make_async_copy(
            x_ref, out_ref.at[:, pl.ds(off, C)], sem)
        cp.start()
        cp.wait()

    x = jnp.arange(R * C, dtype=jnp.int32).reshape(R, C).astype(jnp.int8)
    off = jnp.asarray([1280], jnp.int32)
    try:
        out = pl.pallas_call(
            body,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            out_shape=jax.ShapeDtypeStruct((R, NCAP), jnp.int8),
            scratch_shapes=[pltpu.SemaphoreType.DMA],
        )(off, x)
        got = sync(out)
        want = np.asarray(x)
        ok = (got[:, 1280:1280 + C] == want).all()
        print(f"P3 dyn-offset async copy (HBM->HBM cols): "
              f"{'OK' if ok else 'WRONG'}")
        return ok
    except Exception as e:
        print(f"P3 dyn-offset async copy: FAIL ({type(e).__name__}: "
              f"{str(e)[:200]})")
        return False


# ----------------------------------------------------------------- P4
def probe_compact_matmul():
    C = 1024
    R = 64

    def body(x_ref, mask_ref, out_ref, cnt_ref):
        m = mask_ref[:]                                   # (1, C) int32
        pos = jnp.cumsum(m, axis=1) - m                   # exclusive
        liota = jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
        # P[s, d] = 1 iff dest(s) == d and mask[s]
        P = ((pos[0, :, None] == liota[:, :]) &
             (m[0, :, None] > 0)).astype(jnp.int8)
        out_ref[:] = jax.lax.dot_general(
            x_ref[:], P, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.int8)
        cnt_ref[0, 0] = jnp.sum(m)

    rng = np.random.RandomState(0)
    x = rng.randint(-100, 100, (R, C)).astype(np.int8)
    mask = (rng.rand(C) < 0.4).astype(np.int32)
    try:
        out, cnt = pl.pallas_call(
            body,
            in_specs=[pl.BlockSpec((R, C), lambda: (0, 0)),
                      pl.BlockSpec((1, C), lambda: (0, 0))],
            out_specs=[pl.BlockSpec((R, C), lambda: (0, 0)),
                       pl.BlockSpec((1, 1), lambda: (0, 0),
                                    memory_space=pltpu.SMEM)],
            out_shape=[jax.ShapeDtypeStruct((R, C), jnp.int8),
                       jax.ShapeDtypeStruct((1, 1), jnp.int32)],
        )(jnp.asarray(x), jnp.asarray(mask)[None, :])
        got = sync(out)
        k = int(sync(cnt)[0, 0])
        want = x[:, mask.astype(bool)]
        ok = k == mask.sum() and (got[:, :k] == want).all()
        print(f"P4 cumsum+permute-matmul compaction: "
              f"{'OK' if ok else 'WRONG'} (k={k})")
        return ok
    except Exception as e:
        print(f"P4 compaction: FAIL ({type(e).__name__}: {str(e)[:200]})")
        return False


if __name__ == "__main__":
    r = [probe_dyn_sublane(), probe_masked_grid(), probe_dyn_copy(),
         probe_compact_matmul()]
    sys.exit(0 if all(r) else 1)
