"""Standalone validation + timing of the leaf-partition kernel.

Drives scripts/partition_kernel.py (the round-4 rejected leaf-partition
prototype, quarantined here with its carrier layout — see
docs/PARTITION_DESIGN.md for the full record) on synthetic data through two rounds
(root split, then both children) and checks every carried byte against
a numpy simulation; then times a full-N round at 1M columns.

Usage: python scripts/proto_partition.py [ncols]
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import jax
import jax.numpy as jnp

from carrier import (CARRIER_ROWS, TILE,
                                      assemble_carrier, carrier_row_map,
                                      rows_to_f32, rows_to_i32,
                                      rows_to_leaf)
from partition_kernel import (BT, NCOLS_TAB,
                                               allocate_children,
                                               build_step_table,
                                               partition_round)

G, B = 28, 63


def np_carrier_view(carr, rm):
    """Device carrier -> dict of per-col numpy arrays."""
    c = np.asarray(carr)                       # (T, R, 128)
    t = c.shape[0]
    rows = c.transpose(1, 0, 2).reshape(CARRIER_ROWS, t * TILE)
    leaf = (rows[rm["leaf_lo"]].astype(np.int32) & 255) | \
        (rows[rm["leaf_hi"]].astype(np.int32) << 8)
    perm = np.zeros(t * TILE, np.int64)
    for i in range(4):
        perm |= (rows[rm["perm"] + i].astype(np.int64) & 255) << (8 * i)
    perm = perm.astype(np.int32)
    score = rows[rm["score"]:rm["score"] + 4].astype(np.uint8)
    score = (score[0].astype(np.uint32) | (score[1].astype(np.uint32) << 8)
             | (score[2].astype(np.uint32) << 16)
             | (score[3].astype(np.uint32) << 24)).view(np.float32)
    bins = rows[:G].astype(np.uint8)
    wq = rows[rm["wq"]:rm["wq"] + 3].astype(np.int8)
    return dict(leaf=leaf, perm=perm, score=score, bins=bins, wq=wq)


def run_round(src, dst, parents, rng_tab, arena_ptr, cap, rm):
    """One partition round via the real builder + kernel.

    parents: list of dicts with slot, rslot, grp, thr, kl, kr.
    rng_tab: dict slot -> (alloc_t0, alloc_te, span_t0, span_te).
    Returns (new_dst, updated rng_tab, arena_ptr)."""
    W = len(parents)
    span_t0 = jnp.asarray([rng_tab[p["slot"]][2] for p in parents],
                          jnp.int32)
    span_te = jnp.asarray([rng_tab[p["slot"]][3] for p in parents],
                          jnp.int32)
    al_t0 = jnp.asarray([rng_tab[p["slot"]][0] for p in parents],
                        jnp.int32)
    al_te = jnp.asarray([rng_tab[p["slot"]][1] for p in parents],
                        jnp.int32)
    kl = jnp.asarray([p["kl"] for p in parents], jnp.int32)
    kr = jnp.asarray([p["kr"] for p in parents], jnp.int32)
    a_use, e_use, x, arena_ptr = allocate_children(
        al_t0, al_te, kl, kr, jnp.int32(arena_ptr))
    route_cols = jnp.asarray(
        [[p["slot"], p["rslot"], p["grp"], p["thr"], 0, 0, 0, B,
          0, B, 0, B - 1] for p in parents], jnp.int32)
    tab = build_step_table(span_t0, span_te, route_cols, a_use, e_use,
                           jnp.ones(W, bool), cap)
    out = partition_round(src, dst, tab, num_groups=G, grid_cap=cap)
    a_use, e_use, x = map(np.asarray, (a_use, e_use, x))
    kl_n, kr_n = np.asarray(kl), np.asarray(kr)
    for i, p in enumerate(parents):
        tl = -(-int(kl_n[i]) // TILE)
        tr = -(-int(kr_n[i]) // TILE)
        rng_tab[p["slot"]] = (int(a_use[i]), int(x[i]), int(a_use[i]),
                              int(a_use[i]) + tl)
        rng_tab[p["rslot"]] = (int(x[i]), int(e_use[i]),
                               int(e_use[i]) - tr, int(e_use[i]))
    return out, rng_tab, int(np.asarray(arena_ptr))


def check_children(view, rng_tab, parent, expect_l, expect_r, rm):
    """expect_l/r: dicts perm -> (bins col, wq col, score)."""
    for slot, expect in ((parent["slot"], expect_l),
                         (parent["rslot"], expect_r)):
        t0, te = rng_tab[slot][2], rng_tab[slot][3]
        cols = np.arange(t0 * TILE, te * TILE)
        live = cols[view["leaf"][cols] == slot]
        perms = view["perm"][live]
        assert len(perms) == len(expect), \
            f"slot {slot}: {len(perms)} live vs {len(expect)} expected"
        assert len(set(perms.tolist())) == len(perms), "dup perms"
        for c, pm in zip(live, perms):
            eb, ew, es = expect[int(pm)]
            assert (view["bins"][:, c] == eb).all(), f"bins mismatch @{c}"
            assert (view["wq"][:, c] == ew).all(), f"wq mismatch @{c}"
            assert view["score"][c] == es, f"score mismatch @{c}"
    # within each child's span, only that child's live columns appear
    # (the alloc gap between spans is never written NOR read — readers
    # only stream spans — so stale donated-buffer bytes there are fine)
    pl_, pr = parent["slot"], parent["rslot"]
    for slot, other in ((pl_, pr), (pr, pl_)):
        t0, te = rng_tab[slot][2], rng_tab[slot][3]
        span_leafs = view["leaf"][t0 * TILE:te * TILE]
        assert not (span_leafs == other).any(), \
            f"sibling {other} cols inside slot {slot}'s span"


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 60_000
    rng = np.random.RandomState(0)
    tiles = -(-n // TILE)
    root_alloc = tiles + 8          # ceil-rounding slack for the root
    # arena tail: a quarter again, BT-aligned total
    t_cap = -(-int(root_alloc * 1.25 + 2 * BT) // BT) * BT
    rm = carrier_row_map(G)

    bins = rng.randint(0, B, (n, G)).astype(np.uint8)
    score = rng.randn(n).astype(np.float32)
    label = rng.randint(0, 2, n).astype(np.float32)
    carr = assemble_carrier(jnp.asarray(bins), jnp.asarray(score),
                            jnp.asarray(label), jnp.ones(n, jnp.float32),
                            num_tiles=t_cap, num_groups=G)
    # wq rows: random int8
    wq = rng.randint(-100, 100, (3, n)).astype(np.int8)
    carr_np = np.asarray(carr)
    rowsv = carr_np.transpose(1, 0, 2).reshape(CARRIER_ROWS, t_cap * TILE)
    rowsv[rm["wq"]:rm["wq"] + 3, :n] = wq
    carr = jnp.asarray(rowsv.reshape(CARRIER_ROWS, t_cap, TILE)
                       .transpose(1, 0, 2))
    other = jnp.zeros_like(carr)

    cap = t_cap // BT + 8
    arena_ptr = root_alloc  # arena tail right after the root alloc
    rng_tab = {0: (0, root_alloc, 0, tiles)}

    def expected_split(live_dict, grp, thr):
        el, er = {}, {}
        for pm, (eb, ew, es) in live_dict.items():
            (el if eb[grp] <= thr else er)[pm] = (eb, ew, es)
        return el, er

    live0 = {int(i): (bins[i], wq[:, i], score[i]) for i in range(n)}
    p1 = dict(slot=0, rslot=1, grp=3, thr=25)
    el, er = expected_split(live0, p1["grp"], p1["thr"])
    p1["kl"], p1["kr"] = len(el), len(er)

    out, rng_tab, arena_ptr = run_round(carr, other, [p1], rng_tab,
                                        arena_ptr, cap, rm)
    view = np_carrier_view(out, rm)
    check_children(view, rng_tab, p1, el, er, rm)
    print(f"round 1 OK: kl={p1['kl']} kr={p1['kr']} "
          f"spans L={rng_tab[0]} R={rng_tab[1]}")

    # round 2: split both children (ping-pong back into the original)
    p2a = dict(slot=0, rslot=2, grp=7, thr=40)
    e2l, e2r = expected_split(el, p2a["grp"], p2a["thr"])
    p2a["kl"], p2a["kr"] = len(e2l), len(e2r)
    p2b = dict(slot=1, rslot=3, grp=11, thr=10)
    e3l, e3r = expected_split(er, p2b["grp"], p2b["thr"])
    p2b["kl"], p2b["kr"] = len(e3l), len(e3r)
    out2, rng_tab, arena_ptr = run_round(out, carr, [p2a, p2b], rng_tab,
                                         arena_ptr, cap, rm)
    view2 = np_carrier_view(out2, rm)
    check_children(view2, rng_tab, p2a, e2l, e2r, rm)
    check_children(view2, rng_tab, p2b, e3l, e3r, rm)
    print(f"round 2 OK: ({p2a['kl']},{p2a['kr']}) / "
          f"({p2b['kl']},{p2b['kr']})")
    print("CORRECTNESS OK")


if __name__ == "__main__":
    main()


def timing(n=1_000_000):
    """Full-N round timing: split the root repeatedly (ping-pong inside
    one jit via fori_loop), two loop counts to cancel dispatch."""
    import functools
    rng = np.random.RandomState(0)
    tiles = -(-n // TILE)
    root_alloc = tiles + 8
    t_cap = -(-int(root_alloc * 1.25 + 2 * BT) // BT) * BT
    rm = carrier_row_map(G)
    bins = rng.randint(0, B, (n, G)).astype(np.uint8)
    carr = assemble_carrier(jnp.asarray(bins), jnp.zeros(n, jnp.float32),
                            jnp.zeros(n, jnp.float32),
                            jnp.ones(n, jnp.float32),
                            num_tiles=t_cap, num_groups=G)
    other = jnp.zeros_like(carr)
    cap = t_cap // BT + 8
    kl = int((bins[:, 3] <= 25).sum())
    route_cols = jnp.asarray([[0, 1, 3, 25, 0, 0, 0, B, 0, B, 0, B - 1]],
                             jnp.int32)
    a_use, e_use, x, _ = allocate_children(
        jnp.asarray([0]), jnp.asarray([root_alloc]), jnp.asarray([kl]),
        jnp.asarray([n - kl]), jnp.int32(root_alloc))
    tab = build_step_table(jnp.asarray([0]), jnp.asarray([tiles]),
                           route_cols, a_use, e_use,
                           jnp.ones(1, bool), cap)
    from partition_kernel import partition_round as pr
    pr_nojit = pr.__wrapped__   # un-jitted: called inside our own jit

    import time as _t
    for loops in (4, 16):
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def many(a, b, tab, loops=loops):
            def body(i, ab):
                a, b = ab
                out = pr_nojit(a, b, tab, num_groups=G, grid_cap=cap)
                return (out, a)
            return jax.lax.fori_loop(0, loops, body, (a, b))
        o = many(carr, other, tab)
        _ = np.asarray(o[0][0, 0])
        carr, other = o   # keep buffers alive/valid
        best = float("inf")
        for _i in range(3):
            t0 = _t.perf_counter()
            o = many(carr, other, tab)
            _ = np.asarray(o[0][0, 0])
            carr, other = o
            best = min(best, _t.perf_counter() - t0)
        if loops == 4:
            t4 = best
        else:
            t16 = best
    per_round = (t16 - t4) / 12
    print(f"partition full-N round @ {n}: {per_round*1e3:.3f} ms "
          f"(cap={cap} steps)")
