#!/usr/bin/env python
"""Serving load generator: closed/open-loop clients against the
lightgbm_tpu HTTP frontend, reporting p50/p99 latency and dispatch
amortization vs offered load.

Runs the whole stack in-process (train a tiny model — or load
SERVE_MODEL — publish it warm, mount the frontend on an ephemeral
port), fires real HTTP requests from concurrent client threads, and
reads the serving telemetry counters for the numbers no client can
see: coalesced dispatches, batch fill, queue wait.  Used two ways:

- ``scripts/bench_smoke.sh`` runs it as the serve probe
  (``tests/test_bench_smoke.py`` asserts parity, coalescing,
  p99 bound and clean drain on the JSON it writes), and
- by hand against capacity questions: sweep SERVE_CLIENTS /
  SERVE_MODE=open SERVE_RATE and read the shed rate + p99 curve
  (docs/SERVING.md, capacity planning).

Usage:  python scripts/serve_bench.py [OUT.json]

Env knobs (defaults in parens): SERVE_CLIENTS (8) concurrent client
threads; SERVE_REQUESTS (24) requests per client; SERVE_ROWS ("1")
comma list of request row counts cycled per request; SERVE_MODE
(closed) closed|open; SERVE_RATE (200) open-loop offered requests/s
across all clients; SERVE_DEADLINE_MS (5) serve_batch_deadline_ms;
SERVE_MODEL ("") model file to serve instead of the built-in tiny
model (needs SERVE_FEATURES for row width).
"""
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _env_int(name, default):
    return int(os.environ.get(name, default))


def build_model(features=8, rows=400, iters=6):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(7)
    X = rng.randn(rows, features)
    y = X[:, 0] - 0.3 * X[:, 1]
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), iters, verbose_eval=False)
    return bst, X


def run_bench(bst, X, clients=8, requests=24, rows_spec=(1,),
              mode="closed", rate=200.0, deadline_ms=5.0) -> dict:
    """Serve ``bst`` in-process and drive it with ``clients``
    concurrent threads; returns the result record (latencies from the
    clients, amortization/fill from the telemetry counters, parity
    vs direct predict, drain state)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import ModelRegistry, ServingFrontend
    from lightgbm_tpu.telemetry import TELEMETRY, hist_quantile

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    cfg = Config.from_params({
        "verbose": -1,
        "serve_batch_deadline_ms": deadline_ms,
    })
    registry = ModelRegistry(cfg)
    registry.publish("bench", bst)
    frontend = ServingFrontend(registry, cfg)
    port = frontend.start(0).server_address[1]

    rows_spec = tuple(int(r) for r in rows_spec) or (1,)
    lat_ms = [[] for _ in range(clients)]
    sheds = [0] * clients
    failures = []
    # every client's first response is parity-checked against direct
    # predict of the same rows (byte-identical: JSON repr round-trip)
    parity_bad = []
    t_start = time.perf_counter()

    def client(ci):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        interval = clients / rate if mode == "open" else 0.0
        for k in range(requests):
            n = rows_spec[(ci + k) % len(rows_spec)]
            lo = (ci * requests + k * n) % max(X.shape[0] - n, 1)
            rows = X[lo:lo + n]
            body = json.dumps({"rows": rows.tolist()}).encode()
            if mode == "open" and k:
                # open loop: hold the offered rate regardless of
                # response latency (sleep off the schedule, not the
                # reply)
                next_t = t_start + ci * (interval / clients) \
                    + k * interval
                dt = next_t - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/predict/bench", body=body,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
            except Exception as e:
                failures.append(repr(e))
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                continue
            wall = (time.perf_counter() - t0) * 1e3
            if resp.status == 503:
                sheds[ci] += 1
                continue
            if resp.status != 200:
                failures.append(f"HTTP {resp.status}: "
                                f"{payload[:200]!r}")
                continue
            lat_ms[ci].append(wall)
            if k == 0:
                got = json.loads(payload)["predictions"]
                want = bst.predict(rows).tolist()
                if got != want:
                    parity_bad.append((ci, got, want))
        conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start

    # clean drain: stop() drains every batcher queue before returning;
    # grab the entry first (close() empties the registry)
    entry = registry.get("bench")
    frontend.stop(drain=True)
    drained = entry.batcher.closed and entry.batcher.depth() == 0
    c = TELEMETRY.counters()
    hists = TELEMETRY.histograms()
    lats = sorted(x for per in lat_ms for x in per)
    total_ok = len(lats)
    total_shed = sum(sheds)
    dispatches = int(c.get("serve_dispatches", 0))
    reqs = int(c.get("serve_requests", 0))
    fill = hists.get("serve_batch_fill")
    qwait = hists.get("serve_queue_wait_ms")
    qwait_p99 = hist_quantile(qwait, 0.99) if qwait else None
    if qwait_p99 is not None and not np.isfinite(qwait_p99):
        qwait_p99 = None    # overflow bucket: not a JSON number
    out = {
        "mode": mode,
        "clients": clients,
        "requests": reqs,
        "requests_ok": total_ok,
        "shed": total_shed,
        "failures": failures[:5],
        "offered_rps": round(rate if mode == "open"
                             else (reqs / wall_s if wall_s else 0), 1),
        "wall_s": round(wall_s, 3),
        "p50_ms": round(float(np.percentile(lats, 50)), 3) if lats
        else None,
        "p99_ms": round(float(np.percentile(lats, 99)), 3) if lats
        else None,
        "dispatches": dispatches,
        "rows": int(c.get("serve_rows", 0)),
        "coalesced_requests": int(c.get("serve_coalesced_requests", 0)),
        # the number the micro-batcher exists for: requests answered
        # per device dispatch (1.0 = no coalescing)
        "amortization": round(reqs / dispatches, 2) if dispatches
        else None,
        "batch_fill_mean": round(fill["sum"] / fill["count"], 3)
        if fill and fill["count"] else None,
        "queue_wait_p99_ms": qwait_p99,
        "parity": "fail" if (parity_bad or failures) else "pass",
        "drain": "clean" if drained else "dirty",
    }
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    model_file = os.environ.get("SERVE_MODEL", "")
    if model_file:
        import lightgbm_tpu as lgb
        bst = lgb.Booster(model_file=model_file)
        f = bst.num_feature()
        rng = np.random.RandomState(7)
        X = rng.randn(512, f)
    else:
        bst, X = build_model()
    rows_spec = tuple(
        int(r) for r in os.environ.get("SERVE_ROWS", "1").split(",")
        if r.strip())
    out = run_bench(
        bst, X,
        clients=_env_int("SERVE_CLIENTS", 8),
        requests=_env_int("SERVE_REQUESTS", 24),
        rows_spec=rows_spec,
        mode=os.environ.get("SERVE_MODE", "closed"),
        rate=float(os.environ.get("SERVE_RATE", "200")),
        deadline_ms=float(os.environ.get("SERVE_DEADLINE_MS", "5")),
    )
    text = json.dumps(out, indent=1)
    if argv:
        with open(argv[0], "w") as fh:
            fh.write(text + "\n")
        print(f"serve_bench: {out['requests']} requests -> "
              f"{out['dispatches']} dispatches "
              f"(amortization {out['amortization']}), "
              f"p50 {out['p50_ms']} ms p99 {out['p99_ms']} ms, "
              f"parity {out['parity']} -> {argv[0]}", file=sys.stderr)
    else:
        print(text)
    return 0 if out["parity"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
