#!/usr/bin/env python
"""Serving load generator: closed/open-loop clients against the
lightgbm_tpu HTTP frontend, reporting p50/p99 latency and dispatch
amortization vs offered load.

Runs the whole stack in-process (train a tiny model — or load
SERVE_MODEL — publish it warm, mount the frontend on an ephemeral
port), fires real HTTP requests from concurrent client threads, and
reads the serving telemetry counters for the numbers no client can
see: coalesced dispatches, batch fill, queue wait.  Used two ways:

- ``scripts/bench_smoke.sh`` runs it as the serve probe
  (``tests/test_bench_smoke.py`` asserts parity, coalescing,
  p99 bound and clean drain on the JSON it writes), and
- by hand against capacity questions: sweep SERVE_CLIENTS /
  SERVE_MODE=open SERVE_RATE and read the shed rate + p99 curve
  (docs/SERVING.md, capacity planning).

Usage:  python scripts/serve_bench.py [OUT.json]

Env knobs (defaults in parens): SERVE_CLIENTS (8) concurrent client
threads; SERVE_REQUESTS (24) requests per client; SERVE_ROWS ("1")
comma list of request row counts cycled per request; SERVE_MODE
(closed) closed|open; SERVE_RATE (200) open-loop offered requests/s
across all clients; SERVE_DEADLINE_MS (5) serve_batch_deadline_ms;
SERVE_MODEL ("") model file to serve instead of the built-in tiny
model (needs SERVE_FEATURES for row width); SERVE_LANES ("1")
serve_lanes for the base run; SERVE_BODY (json) json|binary request
wire format (binary = the zero-copy application/x-ltpu-f32 frame).

Fleet probes (round 20), each appended as a block in the output JSON:

- ``lane_scaling`` (SERVE_LANE_PROBE=1, default on): the SAME
  closed-loop load run on 1 lane then SERVE_LANE_N (2) simulated
  lanes, with a per-ROW simulated device wall (SERVE_LANE_SIM_MS,
  1.0 ms) standing in for the accelerator so the CPU seam exposes
  real dispatch concurrency; gate: N-lane rows/s >= 1.5x single-lane.
- ``mixed_model`` (SERVE_MIXED_PROBE=1, default on): open-loop
  clients spread across SERVE_MIXED_MODELS (3) co-batched models
  (serve_cobatch=on); lint: fused dispatches < the per-model
  dispatches they replaced, parity per member model.
"""
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _env_int(name, default):
    return int(os.environ.get(name, default))


def build_model(features=8, rows=400, iters=6, seed=7, label_col=0):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    X = rng.randn(rows, features)
    y = X[:, label_col] - 0.3 * X[:, (label_col + 1) % features]
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), iters, verbose_eval=False)
    return bst, X


def _with_sim_wall(bst, sim_row_ms):
    """Wrap the booster's predict with a per-ROW simulated device
    wall (the sleep releases the GIL, exactly like a real dispatch
    blocking on the accelerator) — the seam that lets the CPU smoke
    measure lane CONCURRENCY instead of host-walk arithmetic.  A
    per-dispatch-constant sleep would be useless here: one lane
    coalescing 8 requests into 1 dispatch would pay the same wall as
    2 lanes running 2 dispatches of 4, hiding the scaling entirely."""
    if not sim_row_ms:
        return bst
    orig = bst.predict

    def predict(rows, **kw):
        time.sleep(sim_row_ms * rows.shape[0] / 1e3)
        return orig(rows, **kw)

    bst.predict = predict
    return bst


def run_bench(bst, X, clients=8, requests=24, rows_spec=(1,),
              mode="closed", rate=200.0, deadline_ms=5.0,
              lanes="1", sim_row_ms=0.0, body_format="json",
              predict_kwargs=None, shed_ms=None,
              telemetry_mode="counters", send_trace=False) -> dict:
    """Serve ``bst`` in-process and drive it with ``clients``
    concurrent threads; returns the result record (latencies from the
    clients, amortization/fill from the telemetry counters, parity
    vs direct predict, drain state).  ``telemetry_mode``/``send_trace``
    drive the trace-overhead probe: spans mode with every client
    request carrying an ``X-Ltpu-Trace`` header exercises the full
    propagation path (context parse/mint, span attrs, fan-in links)."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import (BINARY_F32, ModelRegistry,
                                      ServingFrontend)
    from lightgbm_tpu.telemetry import (TELEMETRY, TRACE_HEADER,
                                        hist_quantile, new_span_id,
                                        new_trace_id)

    TELEMETRY.configure(telemetry_mode)
    TELEMETRY.reset()
    params = {
        "verbose": -1,
        "serve_batch_deadline_ms": deadline_ms,
        "serve_lanes": str(lanes),
    }
    if shed_ms is not None:
        params["serve_shed_deadline_ms"] = float(shed_ms)
    cfg = Config.from_params(params)
    kw = dict(predict_kwargs or {})
    _with_sim_wall(bst, sim_row_ms)
    registry = ModelRegistry(cfg)
    registry.publish("bench", bst, predict_kwargs=kw or None)
    frontend = ServingFrontend(registry, cfg)
    port = frontend.start(0).server_address[1]
    binary = body_format == "binary"

    rows_spec = tuple(int(r) for r in rows_spec) or (1,)
    lat_ms = [[] for _ in range(clients)]
    sheds = [0] * clients
    failures = []
    # every client's first response is parity-checked against direct
    # predict of the same rows (byte-identical: JSON repr round-trip)
    parity_bad = []
    t_start = time.perf_counter()

    def client(ci):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        interval = clients / rate if mode == "open" else 0.0
        for k in range(requests):
            n = rows_spec[(ci + k) % len(rows_spec)]
            lo = (ci * requests + k * n) % max(X.shape[0] - n, 1)
            rows = X[lo:lo + n]
            if binary:
                body = np.ascontiguousarray(rows,
                                            dtype="<f4").tobytes()
                ctype = "application/x-ltpu-f32"
            else:
                body = json.dumps({"rows": rows.tolist()}).encode()
                ctype = "application/json"
            if mode == "open" and k:
                # open loop: hold the offered rate regardless of
                # response latency (sleep off the schedule, not the
                # reply)
                next_t = t_start + ci * (interval / clients) \
                    + k * interval
                dt = next_t - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
            hdrs = {"Content-Type": ctype}
            if send_trace:
                hdrs[TRACE_HEADER] = \
                    f"{new_trace_id()}-{new_span_id()}"
            t0 = time.perf_counter()
            try:
                conn.request("POST", "/predict/bench", body=body,
                             headers=hdrs)
                resp = conn.getresponse()
                payload = resp.read()
            except Exception as e:
                failures.append(repr(e))
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                continue
            wall = (time.perf_counter() - t0) * 1e3
            if resp.status == 503:
                sheds[ci] += 1
                continue
            if resp.status != 200:
                failures.append(f"HTTP {resp.status}: "
                                f"{payload[:200]!r}")
                continue
            lat_ms[ci].append(wall)
            if k == 0:
                got = json.loads(payload)["predictions"]
                # reference matched to the served route: same predict
                # kwargs, and for binary bodies the f32 wire rows the
                # server actually saw (f32->f64 widening is exact)
                ref_rows = (rows.astype("<f4").astype(np.float64)
                            if binary else rows)
                want = bst.predict(ref_rows, **kw).tolist()
                if got != want:
                    parity_bad.append((ci, got, want))
        conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start

    # clean drain: stop() drains every batcher queue before returning;
    # grab the entry first (close() empties the registry)
    entry = registry.get("bench")
    frontend.stop(drain=True)
    drained = entry.batcher.closed and entry.batcher.depth() == 0
    c = TELEMETRY.counters()
    hists = TELEMETRY.histograms()
    lats = sorted(x for per in lat_ms for x in per)
    total_ok = len(lats)
    total_shed = sum(sheds)
    dispatches = int(c.get("serve_dispatches", 0))
    reqs = int(c.get("serve_requests", 0))
    fill = hists.get("serve_batch_fill")
    qwait = hists.get("serve_queue_wait_ms")
    qwait_p99 = hist_quantile(qwait, 0.99) if qwait else None
    if qwait_p99 is not None and not np.isfinite(qwait_p99):
        qwait_p99 = None    # overflow bucket: not a JSON number
    out = {
        "mode": mode,
        "clients": clients,
        "lanes": int(lanes) if str(lanes).isdigit() else str(lanes),
        "body": body_format,
        "requests": reqs,
        "requests_ok": total_ok,
        "shed": total_shed,
        "failures": failures[:5],
        "offered_rps": round(rate if mode == "open"
                             else (reqs / wall_s if wall_s else 0), 1),
        "wall_s": round(wall_s, 3),
        "p50_ms": round(float(np.percentile(lats, 50)), 3) if lats
        else None,
        "p99_ms": round(float(np.percentile(lats, 99)), 3) if lats
        else None,
        "dispatches": dispatches,
        "rows": int(c.get("serve_rows", 0)),
        "coalesced_requests": int(c.get("serve_coalesced_requests", 0)),
        # the number the micro-batcher exists for: requests answered
        # per device dispatch (1.0 = no coalescing)
        "amortization": round(reqs / dispatches, 2) if dispatches
        else None,
        "batch_fill_mean": round(fill["sum"] / fill["count"], 3)
        if fill and fill["count"] else None,
        "queue_wait_p99_ms": qwait_p99,
        "rows_per_s": round(int(c.get("serve_rows", 0)) / wall_s, 1)
        if wall_s else None,
        "lane_dispatches": int(c.get("serve_lane_dispatches", 0)),
        "steals": int(c.get("serve_steals", 0)),
        "lane_stalls": int(c.get("serve_lane_stalls", 0)),
        "parity": "fail" if (parity_bad or failures) else "pass",
        "drain": "clean" if drained else "dirty",
    }
    return out


def lane_scaling_probe(lane_n=2, sim_row_ms=1.0, clients=8,
                       requests=8, rows=8) -> dict:
    """The 2-lane throughput gate: the SAME closed-loop load through
    1 lane then ``lane_n`` simulated lanes, with the per-row device
    wall standing in for the accelerator.  Per-row scores never
    depend on lane routing (the parity field re-checks), so the only
    thing allowed to change is the wall clock."""
    results = {}
    for n in (1, lane_n):
        bst, X = build_model()
        r = run_bench(bst, X, clients=clients, requests=requests,
                      rows_spec=(rows,), mode="closed",
                      deadline_ms=2.0, lanes=str(n),
                      sim_row_ms=sim_row_ms, shed_ms=60_000.0)
        results[n] = r
    r1, rn = results[1], results[lane_n]
    ratio = (rn["rows_per_s"] / r1["rows_per_s"]
             if r1["rows_per_s"] else None)
    return {
        "lanes": lane_n,
        "sim_row_ms": sim_row_ms,
        "single_lane_rows_per_s": r1["rows_per_s"],
        "multi_lane_rows_per_s": rn["rows_per_s"],
        "scaling_x": round(ratio, 2) if ratio else None,
        "steals": rn["steals"],
        "parity": ("pass" if r1["parity"] == rn["parity"] == "pass"
                   else "fail"),
        "drain": ("clean" if r1["drain"] == rn["drain"] == "clean"
                  else "dirty"),
        # the scale-out gate (docs/SERVING.md): 2 lanes must buy at
        # least 1.5x rows/s on the simulated device wall
        "gate": ("pass" if ratio is not None and ratio >= 1.5
                 else "fail"),
    }


def trace_overhead_probe(clients=8, requests=24) -> dict:
    """The tracing-cost gate (docs/OBSERVABILITY.md, Tracing): the
    SAME closed-loop load in telemetry=spans twice — no trace headers
    vs EVERY request carrying an X-Ltpu-Trace header — so the p50
    delta isolates the per-request cost this round adds (header
    parse, context mint/set/clear, span trace attrs, fan-in link
    capture, header echo) from the pre-existing spans-mode observer
    effect, which both runs pay identically.  The host-side design
    target is <5%; the gate bound is generous (25%) because a CPU
    smoke's p50 jitter dwarfs the microseconds under test."""
    results = {}
    for label, tel_mode, send in (("off", "spans", False),
                                  ("on", "spans", True)):
        bst, X = build_model()
        results[label] = run_bench(
            bst, X, clients=clients, requests=requests,
            rows_spec=(1,), mode="closed", deadline_ms=2.0,
            shed_ms=60_000.0, telemetry_mode=tel_mode,
            send_trace=send)
    p50_off = results["off"]["p50_ms"]
    p50_on = results["on"]["p50_ms"]
    pct = (100.0 * (p50_on - p50_off) / p50_off
           if p50_off else None)
    return {
        "p50_ms_tracing_off": p50_off,
        "p50_ms_tracing_on": p50_on,
        "overhead_pct": round(pct, 2) if pct is not None else None,
        "parity": ("pass" if results["off"]["parity"]
                   == results["on"]["parity"] == "pass" else "fail"),
        "gate": ("pass" if pct is not None and pct < 25.0
                 else "fail"),
    }


def run_mixed_bench(n_models=3, clients=6, requests=10, rate=300.0,
                    deadline_ms=10.0, lanes="1") -> dict:
    """Open-loop mixed-model co-batching probe: ``n_models``
    compatible models published with ``serve_cobatch=on``, clients
    spreading requests across ALL of them.  Reads the fused-dispatch
    counters for the amortization lint (fused dispatches < the
    per-model dispatches they replaced) and parity-checks each
    member against its own direct predict."""
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.serving import ModelRegistry, ServingFrontend
    from lightgbm_tpu.telemetry import TELEMETRY

    TELEMETRY.configure("counters")
    TELEMETRY.reset()
    cfg = Config.from_params({
        "verbose": -1,
        "serve_batch_deadline_ms": deadline_ms,
        "serve_lanes": str(lanes),
        "serve_cobatch": "on",
        "predict_warm_buckets": (1, 16),
    })
    registry = ModelRegistry(cfg)
    frontend = ServingFrontend(registry, cfg)
    names = []
    X = None
    with tempfile.TemporaryDirectory() as td:
        for i in range(n_models):
            bst, Xi = build_model(seed=7 + i, label_col=i % 4,
                                  iters=4 + i)
            X = Xi if X is None else X
            path = os.path.join(td, f"m{i}.txt")
            bst.save_model(path)
            # file-loaded + device-pinned: the level-descent route
            # the fused program replicates byte-for-byte
            registry.publish(f"m{i}", path,
                             predict_kwargs={"device": True})
            names.append(f"m{i}")
    entries = {n: registry.get(n) for n in names}
    fused_members = sorted(
        entries[names[0]].cobatch.names) if \
        entries[names[0]].cobatch is not None else []
    port = frontend.start(0).server_address[1]

    lat_ms = []
    failures = []
    parity_bad = []
    t_start = time.perf_counter()

    def client(ci):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=60)
        interval = clients / rate
        checked = set()
        for k in range(requests):
            name = names[(ci + k) % len(names)]
            n = 1 + (ci + k) % 3
            lo = (ci * requests + k) % max(X.shape[0] - n, 1)
            rows = X[lo:lo + n]
            next_t = t_start + ci * (interval / clients) + k * interval
            dt = next_t - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            t0 = time.perf_counter()
            try:
                conn.request(
                    "POST", f"/predict/{name}",
                    body=json.dumps({"rows": rows.tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = resp.read()
            except Exception as e:
                failures.append(repr(e))
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=60)
                continue
            if resp.status != 200:
                failures.append(f"HTTP {resp.status}: "
                                f"{payload[:200]!r}")
                continue
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            if name not in checked:
                checked.add(name)
                got = json.loads(payload)["predictions"]
                want = entries[name].booster.predict(
                    rows, device=True).tolist()
                if got != want:
                    parity_bad.append((name, ci))
        conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start
    frontend.stop(drain=True)
    c = TELEMETRY.counters()
    lats = sorted(lat_ms)
    fused_disp = int(c.get("serve_cobatch_dispatches", 0))
    fused_models = int(c.get("serve_cobatch_fused_models", 0))
    return {
        "mode": "open",
        "models": len(names),
        "fused_group": fused_members,
        "clients": clients,
        "rate_rps": rate,
        "lanes": int(lanes) if str(lanes).isdigit() else str(lanes),
        "requests": int(c.get("serve_requests", 0)),
        "requests_ok": len(lats),
        "failures": failures[:5],
        "wall_s": round(wall_s, 3),
        "p50_ms": round(float(np.percentile(lats, 50)), 3)
        if lats else None,
        "p99_ms": round(float(np.percentile(lats, 99)), 3)
        if lats else None,
        "cobatch_dispatches": fused_disp,
        "cobatch_fused_models": fused_models,
        # the amortization lint: one fused dispatch answered traffic
        # that solo batchers would have paid `fused_models` dispatches
        # for — strictly fewer means the fusion actually amortized
        "cobatch_amortized": bool(fused_disp
                                  and fused_disp < fused_models),
        "parity": "fail" if (parity_bad or failures) else "pass",
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    model_file = os.environ.get("SERVE_MODEL", "")
    if model_file:
        import lightgbm_tpu as lgb
        bst = lgb.Booster(model_file=model_file)
        f = bst.num_feature()
        rng = np.random.RandomState(7)
        X = rng.randn(512, f)
    else:
        bst, X = build_model()
    rows_spec = tuple(
        int(r) for r in os.environ.get("SERVE_ROWS", "1").split(",")
        if r.strip())
    out = run_bench(
        bst, X,
        clients=_env_int("SERVE_CLIENTS", 8),
        requests=_env_int("SERVE_REQUESTS", 24),
        rows_spec=rows_spec,
        mode=os.environ.get("SERVE_MODE", "closed"),
        rate=float(os.environ.get("SERVE_RATE", "200")),
        deadline_ms=float(os.environ.get("SERVE_DEADLINE_MS", "5")),
        lanes=os.environ.get("SERVE_LANES", "1"),
        body_format=os.environ.get("SERVE_BODY", "json"),
    )
    if os.environ.get("SERVE_LANE_PROBE", "1") != "0":
        out["lane_scaling"] = lane_scaling_probe(
            lane_n=_env_int("SERVE_LANE_N", 2),
            sim_row_ms=float(os.environ.get("SERVE_LANE_SIM_MS",
                                            "1.0")))
    if os.environ.get("SERVE_MIXED_PROBE", "1") != "0":
        out["mixed_model"] = run_mixed_bench(
            n_models=_env_int("SERVE_MIXED_MODELS", 3),
            rate=float(os.environ.get("SERVE_MIXED_RATE", "300")))
    if os.environ.get("SERVE_TRACE_PROBE", "1") != "0":
        out["trace_overhead"] = trace_overhead_probe()
    text = json.dumps(out, indent=1)
    if argv:
        with open(argv[0], "w") as fh:
            fh.write(text + "\n")
        print(f"serve_bench: {out['requests']} requests -> "
              f"{out['dispatches']} dispatches "
              f"(amortization {out['amortization']}), "
              f"p50 {out['p50_ms']} ms p99 {out['p99_ms']} ms, "
              f"parity {out['parity']} -> {argv[0]}", file=sys.stderr)
        ls = out.get("lane_scaling")
        if ls:
            print(f"serve_bench lane_scaling: 1 lane "
                  f"{ls['single_lane_rows_per_s']} rows/s -> "
                  f"{ls['lanes']} lanes "
                  f"{ls['multi_lane_rows_per_s']} rows/s "
                  f"({ls['scaling_x']}x, gate {ls['gate']})",
                  file=sys.stderr)
        mm = out.get("mixed_model")
        if mm:
            print(f"serve_bench mixed_model: {mm['models']} models, "
                  f"{mm['cobatch_dispatches']} fused dispatches for "
                  f"{mm['cobatch_fused_models']} model-dispatches "
                  f"(amortized={mm['cobatch_amortized']}, parity "
                  f"{mm['parity']})", file=sys.stderr)
        to = out.get("trace_overhead")
        if to:
            print(f"serve_bench trace_overhead: p50 "
                  f"{to['p50_ms_tracing_off']} ms untraced -> "
                  f"{to['p50_ms_tracing_on']} ms traced "
                  f"({to['overhead_pct']}%, gate {to['gate']})",
                  file=sys.stderr)
    else:
        print(text)
    ok = out["parity"] == "pass"
    ls = out.get("lane_scaling")
    if ls is not None:
        ok = ok and ls["gate"] == "pass" and ls["parity"] == "pass"
    mm = out.get("mixed_model")
    if mm is not None:
        ok = ok and mm["parity"] == "pass" and mm["cobatch_amortized"]
    to = out.get("trace_overhead")
    if to is not None:
        ok = ok and to["parity"] == "pass" and to["gate"] == "pass"
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
