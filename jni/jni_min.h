/* JNI header shim: with a real JDK the genuine <jni.h> is used (the
 * binding then carries the exact ABI a JVM expects); without one, a
 * minimal self-consistent subset lets the binding COMPILE AND RUN
 * against the fake-JNIEnv host (tests/jni_host_driver.c) — every line
 * of the JNI functions executes, no JVM required.  Mirrors the role of
 * the reference's swig/lightgbmlib.i (which also just marshals arrays
 * and strings over the LGBM_* C ABI). */
#pragma once

#if defined(__has_include)
#if __has_include(<jni.h>)
#define LGBM_TPU_REAL_JNI 1
#include <jni.h>
#endif
#endif

#ifndef LGBM_TPU_REAL_JNI
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int32_t jint;
typedef int64_t jlong;
typedef double jdouble;
typedef float jfloat;
typedef uint8_t jboolean;
typedef int32_t jsize;

typedef struct _jobject* jobject;
typedef jobject jclass;
typedef jobject jstring;
typedef jobject jarray;
typedef jarray jdoubleArray;
typedef jarray jintArray;
typedef jarray jfloatArray;
typedef jarray jobjectArray;
typedef jobject jthrowable;

struct JNINativeInterface_;
typedef const struct JNINativeInterface_* JNIEnv;

/* only the slots the binding uses; the stub host fills them with its
 * own implementations.  (Real-JVM builds never see this struct.) */
struct JNINativeInterface_ {
  jclass (*FindClass)(JNIEnv*, const char*);
  jint (*ThrowNew)(JNIEnv*, jclass, const char*);
  const char* (*GetStringUTFChars)(JNIEnv*, jstring, jboolean*);
  void (*ReleaseStringUTFChars)(JNIEnv*, jstring, const char*);
  jsize (*GetArrayLength)(JNIEnv*, jarray);
  jdoubleArray (*NewDoubleArray)(JNIEnv*, jsize);
  jdouble* (*GetDoubleArrayElements)(JNIEnv*, jdoubleArray, jboolean*);
  void (*ReleaseDoubleArrayElements)(JNIEnv*, jdoubleArray, jdouble*,
                                     jint);
  void (*SetDoubleArrayRegion)(JNIEnv*, jdoubleArray, jsize, jsize,
                               const jdouble*);
  jstring (*NewStringUTF)(JNIEnv*, const char*);
  jobjectArray (*NewObjectArray)(JNIEnv*, jsize, jclass, jobject);
  void (*SetObjectArrayElement)(JNIEnv*, jobjectArray, jsize, jobject);
  jobject (*GetObjectArrayElement)(JNIEnv*, jobjectArray, jsize);
  jint* (*GetIntArrayElements)(JNIEnv*, jintArray, jboolean*);
  void (*ReleaseIntArrayElements)(JNIEnv*, jintArray, jint*, jint);
  jfloat* (*GetFloatArrayElements)(JNIEnv*, jfloatArray, jboolean*);
  void (*ReleaseFloatArrayElements)(JNIEnv*, jfloatArray, jfloat*, jint);
};

#define JNIEXPORT
#define JNICALL
#define JNI_ABORT 2

#ifdef __cplusplus
}
#endif
#endif /* !LGBM_TPU_REAL_JNI */
