package com.lightgbm.tpu;

/**
 * Java surface over the TPU framework's C ABI (liblgbm_tpu.so via
 * liblgbm_tpu_jni.so) — the analog of the reference's SWIG-generated
 * lightgbmlib (swig/lightgbmlib.i).
 *
 * Build (needs a JDK):
 *   gcc -shared -fPIC -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *       jni/lightgbm_jni.c -L lightgbm_tpu/native -llgbm_tpu \
 *       -Wl,-rpath,$PWD/lightgbm_tpu/native -o liblgbm_tpu_jni.so
 *   javac jni/LightGBMNative.java
 *
 * Example:
 *   long ds = LightGBMNative.datasetCreateFromMat(x, n, f,
 *       "objective=binary");
 *   LightGBMNative.datasetSetField(ds, "label", y);
 *   long bst = LightGBMNative.boosterCreate(ds, "objective=binary");
 *   for (int i = 0; i < 100; i++)
 *       LightGBMNative.boosterUpdateOneIter(bst);
 *   double[] pred = LightGBMNative.boosterPredictForMat(bst, x, n, f,
 *       0, -1);
 */
public final class LightGBMNative {
    static {
        System.loadLibrary("lgbm_tpu_jni");
    }

    private LightGBMNative() {}

    public static native long datasetCreateFromMat(
        double[] data, int nrow, int ncol, String params);
    public static native long datasetCreateFromMatWithReference(
        double[] data, int nrow, int ncol, String params,
        long reference);
    public static native long datasetCreateFromFile(
        String filename, String params);
    public static native long datasetCreateFromCSR(
        int[] indptr, int[] indices, double[] values, int numCol,
        String params);
    public static native long datasetGetSubset(
        long handle, int[] usedRowIndices, String params);
    public static native void datasetSetField(
        long handle, String field, double[] data);
    public static native int datasetGetNumData(long handle);
    public static native int datasetGetNumFeature(long handle);
    public static native void datasetSaveBinary(
        long handle, String filename);
    public static native void datasetSetFeatureNames(
        long handle, String[] names);
    public static native String[] datasetGetFeatureNames(long handle);
    public static native void datasetFree(long handle);

    public static native long boosterCreate(long dataset, String params);
    public static native long boosterCreateFromModelfile(String filename);
    public static native long boosterLoadModelFromString(String model);
    public static native void boosterAddValidData(
        long handle, long validDataset);
    public static native int boosterUpdateOneIter(long handle);
    public static native int boosterUpdateOneIterCustom(
        long handle, float[] grad, float[] hess);
    public static native void boosterRollbackOneIter(long handle);
    public static native int boosterGetNumClasses(long handle);
    public static native int boosterGetCurrentIteration(long handle);
    public static native int boosterNumberOfTotalModel(long handle);
    public static native int boosterGetNumFeature(long handle);
    public static native String[] boosterGetFeatureNames(long handle);
    public static native int boosterGetEvalCounts(long handle);
    public static native String[] boosterGetEvalNames(long handle);
    public static native double[] boosterGetEval(
        long handle, int dataIdx);
    public static native void boosterResetParameter(
        long handle, String params);
    public static native void boosterResetTrainingData(
        long handle, long dataset);
    public static native void boosterMerge(long handle, long other);
    public static native void boosterSaveModel(
        long handle, int numIteration, String filename);
    public static native String boosterSaveModelToString(
        long handle, int numIteration);
    public static native String boosterDumpModel(
        long handle, int numIteration);
    public static native double[] boosterFeatureImportance(
        long handle, int numIteration, int importanceType);
    public static native long boosterCalcNumPredict(
        long handle, int numRow, int predictType, int numIteration);
    public static native double boosterGetLeafValue(
        long handle, int treeIdx, int leafIdx);
    public static native void boosterSetLeafValue(
        long handle, int treeIdx, int leafIdx, double value);
    public static native double[] boosterPredictForMat(
        long handle, double[] data, int nrow, int ncol,
        int predictType, int numIteration);
    public static native double[] boosterPredictForCSR(
        long handle, int[] indptr, int[] indices, double[] values,
        int numCol, int predictType, int numIteration);
    public static native void boosterPredictForFile(
        long handle, String dataFile, int hasHeader, int predictType,
        int numIteration, String resultFile);
    public static native void boosterFree(long handle);
}
