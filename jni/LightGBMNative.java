package com.lightgbm.tpu;

/**
 * Java surface over the TPU framework's C ABI (liblgbm_tpu.so via
 * liblgbm_tpu_jni.so) — the analog of the reference's SWIG-generated
 * lightgbmlib (swig/lightgbmlib.i).
 *
 * Build (needs a JDK):
 *   gcc -shared -fPIC -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *       jni/lightgbm_jni.c -L lightgbm_tpu/native -llgbm_tpu \
 *       -Wl,-rpath,$PWD/lightgbm_tpu/native -o liblgbm_tpu_jni.so
 *   javac jni/LightGBMNative.java
 *
 * Example:
 *   long ds = LightGBMNative.datasetCreateFromMat(x, n, f,
 *       "objective=binary");
 *   LightGBMNative.datasetSetField(ds, "label", y);
 *   long bst = LightGBMNative.boosterCreate(ds, "objective=binary");
 *   for (int i = 0; i < 100; i++)
 *       LightGBMNative.boosterUpdateOneIter(bst);
 *   double[] pred = LightGBMNative.boosterPredictForMat(bst, x, n, f,
 *       0, -1);
 */
public final class LightGBMNative {
    static {
        System.loadLibrary("lgbm_tpu_jni");
    }

    private LightGBMNative() {}

    public static native long datasetCreateFromMat(
        double[] data, int nrow, int ncol, String params);
    public static native void datasetSetField(
        long handle, String field, double[] data);
    public static native void datasetFree(long handle);
    public static native long boosterCreate(long dataset, String params);
    public static native long boosterCreateFromModelfile(String filename);
    public static native int boosterUpdateOneIter(long handle);
    public static native void boosterSaveModel(
        long handle, int numIteration, String filename);
    public static native double[] boosterPredictForMat(
        long handle, double[] data, int nrow, int ncol,
        int predictType, int numIteration);
    public static native void boosterFree(long handle);
}
