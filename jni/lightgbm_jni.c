/* JNI binding over the LGBM_* C ABI (liblgbm_tpu.so) — the TPU
 * framework's analog of the reference's swig/lightgbmlib.i Java
 * wrapper: marshal Java strings/arrays, forward to the C API, raise
 * RuntimeException on nonzero status.
 *
 * Builds two ways:
 *   - real JDK: gcc -shared -fPIC -I$JAVA_HOME/include ...
 *     lightgbm_jni.c -llgbm_tpu  (jni_min.h defers to <jni.h>)
 *   - no JDK (this CI image): the same file compiles against the
 *     stub JNI subset and is EXECUTED by tests/jni_host_driver.c,
 *     which fabricates a JNIEnv function table.
 *
 * Java class: com.lightgbm.tpu.LightGBMNative (jni/LightGBMNative.java)
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "jni_min.h"

/* LGBM_* C ABI (lightgbm_tpu/native/include/lightgbm_tpu_c_api.h) */
typedef void* DatasetHandle;
typedef void* BoosterHandle;
extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromFile(const char*, const char*,
                                      DatasetHandle, DatasetHandle*);
extern int LGBM_DatasetCreateFromMat(const void*, int, int32_t, int32_t,
                                     int, const char*, DatasetHandle,
                                     DatasetHandle*);
extern int LGBM_DatasetCreateFromCSR(const void*, int, const int32_t*,
                                     const void*, int, int64_t, int64_t,
                                     int64_t, const char*, DatasetHandle,
                                     DatasetHandle*);
extern int LGBM_DatasetGetSubset(const DatasetHandle, const int32_t*,
                                 int32_t, const char*, DatasetHandle*);
extern int LGBM_DatasetSetField(DatasetHandle, const char*, const void*,
                                int, int);
extern int LGBM_DatasetGetNumData(DatasetHandle, int32_t*);
extern int LGBM_DatasetGetNumFeature(DatasetHandle, int32_t*);
extern int LGBM_DatasetSaveBinary(DatasetHandle, const char*);
extern int LGBM_DatasetSetFeatureNames(DatasetHandle, const char**, int);
extern int LGBM_DatasetGetFeatureNames(DatasetHandle, char**, int*);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_BoosterCreate(DatasetHandle, const char*, BoosterHandle*);
extern int LGBM_BoosterCreateFromModelfile(const char*, int*,
                                           BoosterHandle*);
extern int LGBM_BoosterLoadModelFromString(const char*, int*,
                                           BoosterHandle*);
extern int LGBM_BoosterAddValidData(BoosterHandle, const DatasetHandle);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int*);
extern int LGBM_BoosterUpdateOneIterCustom(BoosterHandle, const float*,
                                           const float*, int64_t, int*);
extern int LGBM_BoosterRollbackOneIter(BoosterHandle);
extern int LGBM_BoosterGetNumClasses(BoosterHandle, int*);
extern int LGBM_BoosterGetCurrentIteration(BoosterHandle, int*);
extern int LGBM_BoosterNumberOfTotalModel(BoosterHandle, int*);
extern int LGBM_BoosterGetNumFeature(BoosterHandle, int*);
extern int LGBM_BoosterGetFeatureNames(BoosterHandle, int*, char**);
extern int LGBM_BoosterGetEvalCounts(BoosterHandle, int*);
extern int LGBM_BoosterGetEvalNames(BoosterHandle, int*, char**);
extern int LGBM_BoosterGetEval(BoosterHandle, int, int*, double*);
extern int LGBM_BoosterResetParameter(BoosterHandle, const char*);
extern int LGBM_BoosterResetTrainingData(BoosterHandle,
                                         const DatasetHandle);
extern int LGBM_BoosterMerge(BoosterHandle, BoosterHandle);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, const char*);
extern int LGBM_BoosterSaveModelToString(BoosterHandle, int, int64_t,
                                         int64_t*, char*);
extern int LGBM_BoosterDumpModel(BoosterHandle, int, int64_t, int64_t*,
                                 char*);
extern int LGBM_BoosterFeatureImportance(BoosterHandle, int, int,
                                         double*);
extern int LGBM_BoosterCalcNumPredict(BoosterHandle, int, int, int,
                                      int64_t*);
extern int LGBM_BoosterGetLeafValue(BoosterHandle, int, int, double*);
extern int LGBM_BoosterSetLeafValue(BoosterHandle, int, int, double);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void*, int,
                                     int32_t, int32_t, int, int, int,
                                     const char*, int64_t*, double*);
extern int LGBM_BoosterPredictForCSR(BoosterHandle, const void*, int,
                                     const int32_t*, const void*, int,
                                     int64_t, int64_t, int64_t, int, int,
                                     const char*, int64_t*, double*);
extern int LGBM_BoosterPredictForFile(BoosterHandle, const char*, int,
                                      int, int, const char*, const char*);
extern int LGBM_BoosterFree(BoosterHandle);

#define C_API_DTYPE_FLOAT64 1
#define C_API_DTYPE_INT32 2

static void throw_on_error(JNIEnv* env, int status) {
  if (status != 0) {
    jclass exc = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, exc, LGBM_GetLastError());
  }
}

/* caller buffers for the LGBM_*Get*Names two-call convention (each
 * slot >= 256 bytes, see lightgbm_tpu_c_api.h) */
static char** alloc_name_bufs(int n) {
  char** v = (char**)malloc(sizeof(char*) * (size_t)(n > 0 ? n : 1));
  for (int i = 0; i < n; ++i) v[i] = (char*)malloc(256);
  return v;
}

static void free_name_bufs(char** v, int n) {
  for (int i = 0; i < n; ++i) free(v[i]);
  free(v);
}

static jobjectArray names_to_java(JNIEnv* env, int n, char** bufs) {
  jclass strcls = (*env)->FindClass(env, "java/lang/String");
  jobjectArray arr = (*env)->NewObjectArray(env, (jsize)n, strcls, NULL);
  for (int i = 0; i < n; ++i) {
    (*env)->SetObjectArrayElement(env, arr, (jsize)i,
                                  (*env)->NewStringUTF(env, bufs[i]));
  }
  return arr;
}

static jdoubleArray doubles_to_java(JNIEnv* env, const double* v,
                                    jsize n) {
  jdoubleArray res = (*env)->NewDoubleArray(env, n);
  (*env)->SetDoubleArrayRegion(env, res, 0, n, v);
  return res;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
    JNIEnv* env, jclass cls, jdoubleArray data, jint nrow, jint ncol,
    jstring params) {
  (void)cls;
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromMat(d, C_API_DTYPE_FLOAT64, nrow, ncol,
                                     1 /* row-major (Java layout) */, p,
                                     NULL, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
    JNIEnv* env, jclass cls, jlong handle, jstring field,
    jdoubleArray data) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, field, NULL);
  jsize n = (*env)->GetArrayLength(env, data);
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  float* buf = (float*)malloc(sizeof(float) * (size_t)n);
  for (jsize i = 0; i < n; ++i) buf[i] = (float)d[i];
  int rc = LGBM_DatasetSetField((DatasetHandle)(intptr_t)handle, f, buf,
                                (int)n, 0 /* float32 */);
  free(buf);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, field, f);
  throw_on_error(env, rc);
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetFree(JNIEnv* env, jclass cls,
                                                 jlong handle) {
  (void)cls;
  throw_on_error(env,
                 LGBM_DatasetFree((DatasetHandle)(intptr_t)handle));
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(JNIEnv* env,
                                                   jclass cls,
                                                   jlong dataset,
                                                   jstring params) {
  (void)cls;
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterCreate((DatasetHandle)(intptr_t)dataset, p, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
    JNIEnv* env, jclass cls, jstring filename) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int iters = 0;
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterCreateFromModelfile(f, &iters, &h);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(JNIEnv* env,
                                                          jclass cls,
                                                          jlong handle) {
  (void)cls;
  int finished = 0;
  throw_on_error(env, LGBM_BoosterUpdateOneIter(
      (BoosterHandle)(intptr_t)handle, &finished));
  return (jint)finished;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
    JNIEnv* env, jclass cls, jlong handle, jint num_iteration,
    jstring filename) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int rc = LGBM_BoosterSaveModel((BoosterHandle)(intptr_t)handle,
                                 (int)num_iteration, f);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
}

JNIEXPORT jdoubleArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
    JNIEnv* env, jclass cls, jlong handle, jdoubleArray data, jint nrow,
    jint ncol, jint predict_type, jint num_iteration) {
  (void)cls;
  int num_class = 1;
  throw_on_error(env, LGBM_BoosterGetNumClasses(
      (BoosterHandle)(intptr_t)handle, &num_class));
  if (num_class < 1) num_class = 1;
  /* worst-case output size by predict type: 0/1 normal/raw ->
   * nrow*num_class; 2 leaf index -> nrow*num_trees; 3 contrib ->
   * nrow*(ncol+1)*num_class */
  size_t per_row = (size_t)num_class;
  if (predict_type == 2) {
    int iters = 0;
    throw_on_error(env, LGBM_BoosterGetCurrentIteration(
        (BoosterHandle)(intptr_t)handle, &iters));
    per_row = (size_t)(iters < 1 ? 1 : iters) * (size_t)num_class;
  } else if (predict_type == 3) {
    per_row = (size_t)(ncol + 1) * (size_t)num_class;
  }
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  double* out = (double*)malloc(sizeof(double) * (size_t)nrow * per_row);
  int64_t out_len = 0;
  int rc = LGBM_BoosterPredictForMat(
      (BoosterHandle)(intptr_t)handle, d, C_API_DTYPE_FLOAT64, nrow,
      ncol, 1 /* row-major */, (int)predict_type, (int)num_iteration,
      "", &out_len, out);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  if (rc != 0) {
    free(out);
    throw_on_error(env, rc);
    return NULL;
  }
  jdoubleArray res = (*env)->NewDoubleArray(env, (jsize)out_len);
  (*env)->SetDoubleArrayRegion(env, res, 0, (jsize)out_len, out);
  free(out);
  return res;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterFree(JNIEnv* env, jclass cls,
                                                 jlong handle) {
  (void)cls;
  throw_on_error(env,
                 LGBM_BoosterFree((BoosterHandle)(intptr_t)handle));
}

/* ---- round-4 SWIG-breadth tail: dataset surface ------------------- */

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromFile(
    JNIEnv* env, jclass cls, jstring filename, jstring params) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromFile(f, p, NULL, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMatWithReference(
    JNIEnv* env, jclass cls, jdoubleArray data, jint nrow, jint ncol,
    jstring params, jlong reference) {
  (void)cls;
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromMat(d, C_API_DTYPE_FLOAT64, nrow, ncol,
                                     1, p,
                                     (DatasetHandle)(intptr_t)reference,
                                     &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromCSR(
    JNIEnv* env, jclass cls, jintArray indptr, jintArray indices,
    jdoubleArray values, jint num_col, jstring params) {
  (void)cls;
  jsize nindptr = (*env)->GetArrayLength(env, indptr);
  jsize nelem = (*env)->GetArrayLength(env, values);
  jint* ip = (*env)->GetIntArrayElements(env, indptr, NULL);
  jint* ix = (*env)->GetIntArrayElements(env, indices, NULL);
  jdouble* v = (*env)->GetDoubleArrayElements(env, values, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromCSR(ip, C_API_DTYPE_INT32,
                                     (const int32_t*)ix, v,
                                     C_API_DTYPE_FLOAT64,
                                     (int64_t)nindptr, (int64_t)nelem,
                                     (int64_t)num_col, p, NULL, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseDoubleArrayElements(env, values, v, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, indices, ix, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, indptr, ip, JNI_ABORT);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetGetSubset(
    JNIEnv* env, jclass cls, jlong handle, jintArray used_rows,
    jstring params) {
  (void)cls;
  jsize n = (*env)->GetArrayLength(env, used_rows);
  jint* rows = (*env)->GetIntArrayElements(env, used_rows, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetGetSubset((DatasetHandle)(intptr_t)handle,
                                 (const int32_t*)rows, (int32_t)n, p,
                                 &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseIntArrayElements(env, used_rows, rows, JNI_ABORT);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumData(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int32_t n = 0;
  throw_on_error(env, LGBM_DatasetGetNumData(
      (DatasetHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetGetNumFeature(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int32_t n = 0;
  throw_on_error(env, LGBM_DatasetGetNumFeature(
      (DatasetHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetSaveBinary(
    JNIEnv* env, jclass cls, jlong handle, jstring filename) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int rc = LGBM_DatasetSaveBinary((DatasetHandle)(intptr_t)handle, f);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetSetFeatureNames(
    JNIEnv* env, jclass cls, jlong handle, jobjectArray names) {
  (void)cls;
  jsize n = (*env)->GetArrayLength(env, names);
  const char** v = (const char**)malloc(sizeof(char*) * (size_t)n);
  jobject* objs = (jobject*)malloc(sizeof(jobject) * (size_t)n);
  for (jsize i = 0; i < n; ++i) {
    objs[i] = (*env)->GetObjectArrayElement(env, names, i);
    v[i] = (*env)->GetStringUTFChars(env, (jstring)objs[i], NULL);
  }
  int rc = LGBM_DatasetSetFeatureNames((DatasetHandle)(intptr_t)handle,
                                       v, (int)n);
  for (jsize i = 0; i < n; ++i) {
    (*env)->ReleaseStringUTFChars(env, (jstring)objs[i], v[i]);
  }
  free(objs);
  free((void*)v);
  throw_on_error(env, rc);
}

JNIEXPORT jobjectArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetGetFeatureNames(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  int rc = LGBM_DatasetGetFeatureNames((DatasetHandle)(intptr_t)handle,
                                       NULL, &n);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  char** bufs = alloc_name_bufs(n);
  rc = LGBM_DatasetGetFeatureNames((DatasetHandle)(intptr_t)handle,
                                   bufs, &n);
  jobjectArray res = (rc == 0) ? names_to_java(env, n, bufs) : NULL;
  free_name_bufs(bufs, n);
  throw_on_error(env, rc);
  return res;
}

/* ---- round-4 SWIG-breadth tail: booster surface ------------------- */

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterLoadModelFromString(
    JNIEnv* env, jclass cls, jstring model) {
  (void)cls;
  const char* m = (*env)->GetStringUTFChars(env, model, NULL);
  int iters = 0;
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterLoadModelFromString(m, &iters, &h);
  (*env)->ReleaseStringUTFChars(env, model, m);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jstring JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModelToString(
    JNIEnv* env, jclass cls, jlong handle, jint num_iteration) {
  (void)cls;
  int64_t need = 0;
  int rc = LGBM_BoosterSaveModelToString(
      (BoosterHandle)(intptr_t)handle, (int)num_iteration, 0, &need,
      NULL);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  char* buf = (char*)malloc((size_t)need);
  rc = LGBM_BoosterSaveModelToString((BoosterHandle)(intptr_t)handle,
                                     (int)num_iteration, need, &need,
                                     buf);
  jstring res = (rc == 0) ? (*env)->NewStringUTF(env, buf) : NULL;
  free(buf);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT jstring JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterDumpModel(
    JNIEnv* env, jclass cls, jlong handle, jint num_iteration) {
  (void)cls;
  int64_t need = 0;
  int rc = LGBM_BoosterDumpModel((BoosterHandle)(intptr_t)handle,
                                 (int)num_iteration, 0, &need, NULL);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  char* buf = (char*)malloc((size_t)need);
  rc = LGBM_BoosterDumpModel((BoosterHandle)(intptr_t)handle,
                             (int)num_iteration, need, &need, buf);
  jstring res = (rc == 0) ? (*env)->NewStringUTF(env, buf) : NULL;
  free(buf);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIterCustom(
    JNIEnv* env, jclass cls, jlong handle, jfloatArray grad,
    jfloatArray hess) {
  (void)cls;
  jsize n = (*env)->GetArrayLength(env, grad);
  if ((*env)->GetArrayLength(env, hess) != n) {
    jclass exc = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, exc, "grad/hess length mismatch");
    return 0;
  }
  jfloat* g = (*env)->GetFloatArrayElements(env, grad, NULL);
  jfloat* h = (*env)->GetFloatArrayElements(env, hess, NULL);
  int finished = 0;
  int rc = LGBM_BoosterUpdateOneIterCustom(
      (BoosterHandle)(intptr_t)handle, g, h, (int64_t)n, &finished);
  (*env)->ReleaseFloatArrayElements(env, hess, h, JNI_ABORT);
  (*env)->ReleaseFloatArrayElements(env, grad, g, JNI_ABORT);
  throw_on_error(env, rc);
  return (jint)finished;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterRollbackOneIter(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  throw_on_error(env, LGBM_BoosterRollbackOneIter(
      (BoosterHandle)(intptr_t)handle));
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumClasses(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  throw_on_error(env, LGBM_BoosterGetNumClasses(
      (BoosterHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetCurrentIteration(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  throw_on_error(env, LGBM_BoosterGetCurrentIteration(
      (BoosterHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterNumberOfTotalModel(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  throw_on_error(env, LGBM_BoosterNumberOfTotalModel(
      (BoosterHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetNumFeature(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  throw_on_error(env, LGBM_BoosterGetNumFeature(
      (BoosterHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jobjectArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetFeatureNames(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  int rc = LGBM_BoosterGetFeatureNames((BoosterHandle)(intptr_t)handle,
                                       &n, NULL);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  char** bufs = alloc_name_bufs(n);
  rc = LGBM_BoosterGetFeatureNames((BoosterHandle)(intptr_t)handle, &n,
                                   bufs);
  jobjectArray res = (rc == 0) ? names_to_java(env, n, bufs) : NULL;
  free_name_bufs(bufs, n);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterAddValidData(
    JNIEnv* env, jclass cls, jlong handle, jlong valid) {
  (void)cls;
  throw_on_error(env, LGBM_BoosterAddValidData(
      (BoosterHandle)(intptr_t)handle,
      (DatasetHandle)(intptr_t)valid));
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalCounts(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  throw_on_error(env, LGBM_BoosterGetEvalCounts(
      (BoosterHandle)(intptr_t)handle, &n));
  return (jint)n;
}

JNIEXPORT jobjectArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetEvalNames(
    JNIEnv* env, jclass cls, jlong handle) {
  (void)cls;
  int n = 0;
  int rc = LGBM_BoosterGetEvalNames((BoosterHandle)(intptr_t)handle, &n,
                                    NULL);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  char** bufs = alloc_name_bufs(n);
  rc = LGBM_BoosterGetEvalNames((BoosterHandle)(intptr_t)handle, &n,
                                bufs);
  jobjectArray res = (rc == 0) ? names_to_java(env, n, bufs) : NULL;
  free_name_bufs(bufs, n);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT jdoubleArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetEval(
    JNIEnv* env, jclass cls, jlong handle, jint data_idx) {
  (void)cls;
  int cap = 0;
  int rc = LGBM_BoosterGetEvalCounts((BoosterHandle)(intptr_t)handle,
                                     &cap);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  double* vals = (double*)malloc(sizeof(double)
                                 * (size_t)(cap > 0 ? cap : 1));
  int n = 0;
  rc = LGBM_BoosterGetEval((BoosterHandle)(intptr_t)handle,
                           (int)data_idx, &n, vals);
  jdoubleArray res =
      (rc == 0) ? doubles_to_java(env, vals, (jsize)n) : NULL;
  free(vals);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterResetParameter(
    JNIEnv* env, jclass cls, jlong handle, jstring params) {
  (void)cls;
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  int rc = LGBM_BoosterResetParameter((BoosterHandle)(intptr_t)handle,
                                      p);
  (*env)->ReleaseStringUTFChars(env, params, p);
  throw_on_error(env, rc);
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterResetTrainingData(
    JNIEnv* env, jclass cls, jlong handle, jlong dataset) {
  (void)cls;
  throw_on_error(env, LGBM_BoosterResetTrainingData(
      (BoosterHandle)(intptr_t)handle,
      (DatasetHandle)(intptr_t)dataset));
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterMerge(
    JNIEnv* env, jclass cls, jlong handle, jlong other) {
  (void)cls;
  throw_on_error(env, LGBM_BoosterMerge(
      (BoosterHandle)(intptr_t)handle,
      (BoosterHandle)(intptr_t)other));
}

JNIEXPORT jdoubleArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForCSR(
    JNIEnv* env, jclass cls, jlong handle, jintArray indptr,
    jintArray indices, jdoubleArray values, jint num_col,
    jint predict_type, jint num_iteration) {
  (void)cls;
  jsize nindptr = (*env)->GetArrayLength(env, indptr);
  jsize nelem = (*env)->GetArrayLength(env, values);
  int64_t cap = 0;
  int rc = LGBM_BoosterCalcNumPredict(
      (BoosterHandle)(intptr_t)handle, (int)(nindptr - 1),
      (int)predict_type, (int)num_iteration, &cap);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  jint* ip = (*env)->GetIntArrayElements(env, indptr, NULL);
  jint* ix = (*env)->GetIntArrayElements(env, indices, NULL);
  jdouble* v = (*env)->GetDoubleArrayElements(env, values, NULL);
  double* out = (double*)malloc(sizeof(double)
                                * (size_t)(cap > 0 ? cap : 1));
  int64_t out_len = 0;
  rc = LGBM_BoosterPredictForCSR(
      (BoosterHandle)(intptr_t)handle, ip, C_API_DTYPE_INT32,
      (const int32_t*)ix, v, C_API_DTYPE_FLOAT64, (int64_t)nindptr,
      (int64_t)nelem, (int64_t)num_col, (int)predict_type,
      (int)num_iteration, "", &out_len, out);
  (*env)->ReleaseDoubleArrayElements(env, values, v, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, indices, ix, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, indptr, ip, JNI_ABORT);
  jdoubleArray res =
      (rc == 0) ? doubles_to_java(env, out, (jsize)out_len) : NULL;
  free(out);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForFile(
    JNIEnv* env, jclass cls, jlong handle, jstring data_file,
    jint has_header, jint predict_type, jint num_iteration,
    jstring result_file) {
  (void)cls;
  const char* df = (*env)->GetStringUTFChars(env, data_file, NULL);
  const char* rf = (*env)->GetStringUTFChars(env, result_file, NULL);
  int rc = LGBM_BoosterPredictForFile(
      (BoosterHandle)(intptr_t)handle, df, (int)has_header,
      (int)predict_type, (int)num_iteration, "", rf);
  (*env)->ReleaseStringUTFChars(env, result_file, rf);
  (*env)->ReleaseStringUTFChars(env, data_file, df);
  throw_on_error(env, rc);
}

JNIEXPORT jdoubleArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterFeatureImportance(
    JNIEnv* env, jclass cls, jlong handle, jint num_iteration,
    jint importance_type) {
  (void)cls;
  int nfeat = 0;
  int rc = LGBM_BoosterGetNumFeature((BoosterHandle)(intptr_t)handle,
                                     &nfeat);
  if (rc != 0) { throw_on_error(env, rc); return NULL; }
  double* out = (double*)malloc(sizeof(double)
                                * (size_t)(nfeat > 0 ? nfeat : 1));
  rc = LGBM_BoosterFeatureImportance((BoosterHandle)(intptr_t)handle,
                                     (int)num_iteration,
                                     (int)importance_type, out);
  jdoubleArray res =
      (rc == 0) ? doubles_to_java(env, out, (jsize)nfeat) : NULL;
  free(out);
  throw_on_error(env, rc);
  return res;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterCalcNumPredict(
    JNIEnv* env, jclass cls, jlong handle, jint num_row,
    jint predict_type, jint num_iteration) {
  (void)cls;
  int64_t n = 0;
  throw_on_error(env, LGBM_BoosterCalcNumPredict(
      (BoosterHandle)(intptr_t)handle, (int)num_row, (int)predict_type,
      (int)num_iteration, &n));
  return (jlong)n;
}

JNIEXPORT jdouble JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterGetLeafValue(
    JNIEnv* env, jclass cls, jlong handle, jint tree_idx,
    jint leaf_idx) {
  (void)cls;
  double v = 0.0;
  throw_on_error(env, LGBM_BoosterGetLeafValue(
      (BoosterHandle)(intptr_t)handle, (int)tree_idx, (int)leaf_idx,
      &v));
  return (jdouble)v;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterSetLeafValue(
    JNIEnv* env, jclass cls, jlong handle, jint tree_idx, jint leaf_idx,
    jdouble value) {
  (void)cls;
  throw_on_error(env, LGBM_BoosterSetLeafValue(
      (BoosterHandle)(intptr_t)handle, (int)tree_idx, (int)leaf_idx,
      (double)value));
}
