/* JNI binding over the LGBM_* C ABI (liblgbm_tpu.so) — the TPU
 * framework's analog of the reference's swig/lightgbmlib.i Java
 * wrapper: marshal Java strings/arrays, forward to the C API, raise
 * RuntimeException on nonzero status.
 *
 * Builds two ways:
 *   - real JDK: gcc -shared -fPIC -I$JAVA_HOME/include ...
 *     lightgbm_jni.c -llgbm_tpu  (jni_min.h defers to <jni.h>)
 *   - no JDK (this CI image): the same file compiles against the
 *     stub JNI subset and is EXECUTED by tests/jni_host_driver.c,
 *     which fabricates a JNIEnv function table.
 *
 * Java class: com.lightgbm.tpu.LightGBMNative (jni/LightGBMNative.java)
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "jni_min.h"

/* LGBM_* C ABI (lightgbm_tpu/native/include/lgbm_tpu_c_api.h) */
typedef void* DatasetHandle;
typedef void* BoosterHandle;
extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void*, int, int32_t, int32_t,
                                     int, const char*, DatasetHandle,
                                     DatasetHandle*);
extern int LGBM_DatasetSetField(DatasetHandle, const char*, const void*,
                                int, int);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_BoosterCreate(DatasetHandle, const char*, BoosterHandle*);
extern int LGBM_BoosterCreateFromModelfile(const char*, int*,
                                           BoosterHandle*);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int*);
extern int LGBM_BoosterGetNumClasses(BoosterHandle, int*);
extern int LGBM_BoosterGetCurrentIteration(BoosterHandle, int*);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, const char*);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void*, int,
                                     int32_t, int32_t, int, int, int,
                                     const char*, int64_t*, double*);
extern int LGBM_BoosterFree(BoosterHandle);

#define C_API_DTYPE_FLOAT64 1

static void throw_on_error(JNIEnv* env, int status) {
  if (status != 0) {
    jclass exc = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, exc, LGBM_GetLastError());
  }
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetCreateFromMat(
    JNIEnv* env, jclass cls, jdoubleArray data, jint nrow, jint ncol,
    jstring params) {
  (void)cls;
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  DatasetHandle h = NULL;
  int rc = LGBM_DatasetCreateFromMat(d, C_API_DTYPE_FLOAT64, nrow, ncol,
                                     1 /* row-major (Java layout) */, p,
                                     NULL, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetSetField(
    JNIEnv* env, jclass cls, jlong handle, jstring field,
    jdoubleArray data) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, field, NULL);
  jsize n = (*env)->GetArrayLength(env, data);
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  float* buf = (float*)malloc(sizeof(float) * (size_t)n);
  for (jsize i = 0; i < n; ++i) buf[i] = (float)d[i];
  int rc = LGBM_DatasetSetField((DatasetHandle)(intptr_t)handle, f, buf,
                                (int)n, 0 /* float32 */);
  free(buf);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, field, f);
  throw_on_error(env, rc);
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_datasetFree(JNIEnv* env, jclass cls,
                                                 jlong handle) {
  (void)cls;
  throw_on_error(env,
                 LGBM_DatasetFree((DatasetHandle)(intptr_t)handle));
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterCreate(JNIEnv* env,
                                                   jclass cls,
                                                   jlong dataset,
                                                   jstring params) {
  (void)cls;
  const char* p = (*env)->GetStringUTFChars(env, params, NULL);
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterCreate((DatasetHandle)(intptr_t)dataset, p, &h);
  (*env)->ReleaseStringUTFChars(env, params, p);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jlong JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterCreateFromModelfile(
    JNIEnv* env, jclass cls, jstring filename) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int iters = 0;
  BoosterHandle h = NULL;
  int rc = LGBM_BoosterCreateFromModelfile(f, &iters, &h);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
  return (jlong)(intptr_t)h;
}

JNIEXPORT jint JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterUpdateOneIter(JNIEnv* env,
                                                          jclass cls,
                                                          jlong handle) {
  (void)cls;
  int finished = 0;
  throw_on_error(env, LGBM_BoosterUpdateOneIter(
      (BoosterHandle)(intptr_t)handle, &finished));
  return (jint)finished;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterSaveModel(
    JNIEnv* env, jclass cls, jlong handle, jint num_iteration,
    jstring filename) {
  (void)cls;
  const char* f = (*env)->GetStringUTFChars(env, filename, NULL);
  int rc = LGBM_BoosterSaveModel((BoosterHandle)(intptr_t)handle,
                                 (int)num_iteration, f);
  (*env)->ReleaseStringUTFChars(env, filename, f);
  throw_on_error(env, rc);
}

JNIEXPORT jdoubleArray JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterPredictForMat(
    JNIEnv* env, jclass cls, jlong handle, jdoubleArray data, jint nrow,
    jint ncol, jint predict_type, jint num_iteration) {
  (void)cls;
  int num_class = 1;
  throw_on_error(env, LGBM_BoosterGetNumClasses(
      (BoosterHandle)(intptr_t)handle, &num_class));
  if (num_class < 1) num_class = 1;
  /* worst-case output size by predict type: 0/1 normal/raw ->
   * nrow*num_class; 2 leaf index -> nrow*num_trees; 3 contrib ->
   * nrow*(ncol+1)*num_class */
  size_t per_row = (size_t)num_class;
  if (predict_type == 2) {
    int iters = 0;
    throw_on_error(env, LGBM_BoosterGetCurrentIteration(
        (BoosterHandle)(intptr_t)handle, &iters));
    per_row = (size_t)(iters < 1 ? 1 : iters) * (size_t)num_class;
  } else if (predict_type == 3) {
    per_row = (size_t)(ncol + 1) * (size_t)num_class;
  }
  jdouble* d = (*env)->GetDoubleArrayElements(env, data, NULL);
  double* out = (double*)malloc(sizeof(double) * (size_t)nrow * per_row);
  int64_t out_len = 0;
  int rc = LGBM_BoosterPredictForMat(
      (BoosterHandle)(intptr_t)handle, d, C_API_DTYPE_FLOAT64, nrow,
      ncol, 1 /* row-major */, (int)predict_type, (int)num_iteration,
      "", &out_len, out);
  (*env)->ReleaseDoubleArrayElements(env, data, d, JNI_ABORT);
  if (rc != 0) {
    free(out);
    throw_on_error(env, rc);
    return NULL;
  }
  jdoubleArray res = (*env)->NewDoubleArray(env, (jsize)out_len);
  (*env)->SetDoubleArrayRegion(env, res, 0, (jsize)out_len, out);
  free(out);
  return res;
}

JNIEXPORT void JNICALL
Java_com_lightgbm_tpu_LightGBMNative_boosterFree(JNIEnv* env, jclass cls,
                                                 jlong handle) {
  (void)cls;
  throw_on_error(env,
                 LGBM_BoosterFree((BoosterHandle)(intptr_t)handle));
}
