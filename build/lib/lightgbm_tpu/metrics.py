"""Evaluation metrics, computed on device.

TPU-native re-design of the reference metric layer
(reference: src/metric/*.hpp behind the factory metric.cpp:11-53).
Pointwise metrics are elementwise reductions; AUC's tie-aware
sorted-group accumulation (binary_metric.hpp:157-260) and NDCG/MAP's
per-query walks (rank_metric.hpp, dcg_calculator.cpp) become sort +
segment-cumsum formulations.  ``factor_to_bigger_better`` drives early
stopping exactly like the reference (gbdt.cpp:623).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata
from .utils.log import Log


class Metric:
    name = "metric"
    bigger_is_better = False   # factor_to_bigger_better = +1 if True

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label[:num_data]
                                 .astype(np.float32))
        w = metadata.weight
        self.weight = (None if w is None
                       else jnp.asarray(w[:num_data].astype(np.float32)))
        self.sum_weight = (float(num_data) if w is None
                           else float(np.sum(w[:num_data])))

    def eval(self, score: jax.Array, objective=None) -> List[float]:
        raise NotImplementedError

    def names(self) -> List[str]:
        return [self.name]

    def _avg(self, loss: jax.Array):
        if self.weight is None:
            return jnp.sum(loss) / self.sum_weight
        return jnp.sum(loss * self.weight) / self.sum_weight


class _PointwiseMetric(Metric):
    """Analog of RegressionMetric<T> (regression_metric.hpp:16-106):
    objective->ConvertOutput is applied when the objective defines one."""

    def loss(self, label, pred):
        raise NotImplementedError

    def finalize(self, avg_loss):
        return avg_loss

    def eval(self, score, objective=None):
        pred = score
        if objective is not None:
            pred = objective.convert_output(score)
        return [float(self.finalize(self._avg(self.loss(self.label, pred))))]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def loss(self, label, pred):
        return (pred - label) ** 2


class RMSEMetric(_PointwiseMetric):
    name = "rmse"

    def loss(self, label, pred):
        return (pred - label) ** 2

    def finalize(self, avg_loss):
        return jnp.sqrt(avg_loss)


class L1Metric(_PointwiseMetric):
    name = "l1"

    def loss(self, label, pred):
        return jnp.abs(pred - label)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def loss(self, label, pred):
        delta = label - pred
        return jnp.where(delta < 0, (self.config.alpha - 1.0) * delta,
                         self.config.alpha * delta)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def loss(self, label, pred):
        a = self.config.alpha
        diff = pred - label
        return jnp.where(jnp.abs(diff) <= a, 0.5 * diff * diff,
                         a * (jnp.abs(diff) - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def loss(self, label, pred):
        c = self.config.fair_c
        x = jnp.abs(pred - label)
        return c * x - c * c * jnp.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def loss(self, label, pred):
        eps = 1e-10
        pred = jnp.maximum(pred, eps)
        return pred - label * jnp.log(pred)


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def loss(self, label, pred):
        return jnp.abs(label - pred) / jnp.maximum(1.0, jnp.abs(label))


class GammaMetric(_PointwiseMetric):
    """reference regression_metric.hpp:245-261: negative gamma
    log-likelihood with unit shape."""
    name = "gamma"

    def loss(self, label, pred):
        psi = 1.0
        theta = -1.0 / jnp.maximum(pred, 1e-10)
        a = psi
        b = -jnp.log(-theta)
        c = 1.0 / psi * jnp.log(label / psi) - jnp.log(label) \
            - 0.0  # lgamma(1/psi) = 0 for psi=1
        return -((label * theta - b) / a + c)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma-deviance"

    def loss(self, label, pred):
        tmp = label / jnp.maximum(pred, 1e-10)
        return tmp - jnp.log(tmp) - 1.0

    def finalize(self, avg_loss):
        # reference returns sum * 2 (no weight normalization)
        return avg_loss * self.sum_weight * 2.0


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def loss(self, label, pred):
        rho = self.config.tweedie_variance_power
        pred = jnp.maximum(pred, 1e-10)
        a = label * jnp.exp((1 - rho) * jnp.log(pred)) / (1 - rho)
        b = jnp.exp((2 - rho) * jnp.log(pred)) / (2 - rho)
        return -a + b


class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def loss(self, label, prob):
        is_pos = label > 0
        p = jnp.clip(prob, 1e-15, 1 - 1e-15)
        return jnp.where(is_pos, -jnp.log(p), -jnp.log(1 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def loss(self, label, prob):
        pred_pos = prob > 0.5
        return jnp.where((label > 0) == pred_pos, 0.0, 1.0)


class AUCMetric(Metric):
    """Tie-aware AUC (reference binary_metric.hpp:157-260): sum over
    distinct-score groups of neg_w * (pos_w/2 + pos_before)."""
    name = "auc"
    bigger_is_better = True

    def eval(self, score, objective=None):
        label = self.label
        w = (jnp.ones_like(label) if self.weight is None else self.weight)
        order = jnp.argsort(-score, stable=True)
        s = score[order]
        lab = label[order]
        ww = w[order]
        pos = jnp.where(lab > 0, ww, 0.0)
        neg = jnp.where(lab <= 0, ww, 0.0)
        changed = jnp.concatenate([jnp.array([False]), s[1:] != s[:-1]])
        gid = jnp.cumsum(changed.astype(jnp.int32))
        n = s.shape[0]
        seg_pos = jax.ops.segment_sum(pos, gid, num_segments=n)
        seg_neg = jax.ops.segment_sum(neg, gid, num_segments=n)
        pos_before = jnp.concatenate(
            [jnp.zeros(1), jnp.cumsum(seg_pos)[:-1]])
        accum = jnp.sum(seg_neg * (seg_pos * 0.5 + pos_before))
        sum_pos = jnp.sum(pos)
        denom = sum_pos * (self.sum_weight - sum_pos)
        auc = jnp.where(denom > 0, accum / denom, 1.0)
        return [float(auc)]


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective=None):
        # score: (N, K) raw; convert via softmax (or objective transform)
        if objective is not None:
            p = objective.convert_output(score)
        else:
            p = jax.nn.softmax(score, axis=1)
        li = self.label.astype(jnp.int32)
        pt = jnp.take_along_axis(p, li[:, None], axis=1)[:, 0]
        loss = -jnp.log(jnp.clip(pt, 1e-15, None))
        return [float(self._avg(loss))]


class MultiErrorMetric(Metric):
    name = "multi_error"

    def eval(self, score, objective=None):
        li = self.label.astype(jnp.int32)
        pred = jnp.argmax(score, axis=1).astype(jnp.int32)
        return [float(self._avg(jnp.where(pred == li, 0.0, 1.0)))]


class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def loss(self, label, prob):
        p = jnp.clip(prob, 1e-15, 1 - 1e-15)
        return -(label * jnp.log(p) + (1 - label) * jnp.log(1 - p))


class CrossEntropyLambdaMetric(Metric):
    """reference xentropy_metric.hpp xentlambda: loss on hhat scale."""
    name = "cross_entropy_lambda"

    def eval(self, score, objective=None):
        hhat = jnp.log1p(jnp.exp(score))
        w = jnp.ones_like(score) if self.weight is None else self.weight
        z = 1.0 - jnp.exp(-w * hhat)
        z = jnp.clip(z, 1e-15, 1 - 1e-15)
        loss = -(self.label * jnp.log(z) + (1 - self.label) * jnp.log(1 - z))
        return [float(jnp.sum(loss) / self.sum_weight)]


class KLDivMetric(Metric):
    """reference xentropy_metric.hpp kldiv: cross-entropy minus label
    entropy."""
    name = "kldiv"

    def eval(self, score, objective=None):
        p = jnp.clip(jax.nn.sigmoid(score), 1e-15, 1 - 1e-15)
        y = jnp.clip(self.label, 0.0, 1.0)
        ye = jnp.where((y > 0) & (y < 1),
                       y * jnp.log(y) + (1 - y) * jnp.log(1 - y), 0.0)
        ce = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        return [float(self._avg(ce + ye))]


class _RankMetric(Metric):
    """Shared padded-query layout for NDCG/MAP (reference
    rank_metric.hpp + dcg_calculator.cpp)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal(f"The {self.name} metric requires query information")
        qb = metadata.query_boundaries
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        M = int(sizes.max())
        Q = self.num_queries
        idx = np.full((Q, M), -1, dtype=np.int32)
        for q in range(Q):
            idx[q, :sizes[q]] = np.arange(qb[q], qb[q + 1])
        self._qidx = jnp.asarray(idx)
        self._qmask = jnp.asarray(idx >= 0)
        lab = metadata.label[np.maximum(idx, 0)] * (idx >= 0)
        self._qlabel = jnp.asarray(lab.astype(np.float32))
        # query weights: mean of row weights (reference uses query_weights
        # from metadata; approximated as uniform when absent)
        self._qweight = jnp.ones(Q, dtype=jnp.float32)
        self.eval_at = tuple(int(k) for k in self.config.ndcg_eval_at)

    def names(self):
        return [f"{self.name}@{k}" for k in self.eval_at]


class NDCGMetric(_RankMetric):
    name = "ndcg"
    bigger_is_better = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_gain = self.config.label_gain
        if not label_gain:
            label_gain = tuple(float(2 ** i - 1) for i in range(31))
        self._gain = jnp.asarray(np.asarray(label_gain, dtype=np.float32))

    def eval(self, score, objective=None):
        qidx = self._qidx
        qmask = self._qmask
        safe = jnp.maximum(qidx, 0)
        s = jnp.where(qmask, score[safe], -jnp.inf)
        lab = self._qlabel.astype(jnp.int32)
        gains = self._gain[jnp.clip(lab, 0, None)] * qmask

        order = jnp.argsort(-s, axis=1, stable=True)
        sorted_gain = jnp.take_along_axis(gains, order, axis=1)
        ideal_gain = -jnp.sort(-gains, axis=1)
        M = s.shape[1]
        discount = 1.0 / jnp.log2(2.0 + jnp.arange(M, dtype=jnp.float32))
        results = []
        for k in self.eval_at:
            kk = min(k, M)
            dcg = jnp.sum(sorted_gain[:, :kk] * discount[None, :kk], axis=1)
            maxdcg = jnp.sum(ideal_gain[:, :kk] * discount[None, :kk], axis=1)
            ndcg = jnp.where(maxdcg > 0, dcg / maxdcg, 1.0)
            results.append(float(jnp.sum(ndcg * self._qweight)
                                 / jnp.sum(self._qweight)))
        return results


class MAPMetric(_RankMetric):
    name = "map"
    bigger_is_better = True

    def eval(self, score, objective=None):
        qidx = self._qidx
        qmask = self._qmask
        safe = jnp.maximum(qidx, 0)
        s = jnp.where(qmask, score[safe], -jnp.inf)
        rel = (self._qlabel > 0) & qmask
        order = jnp.argsort(-s, axis=1, stable=True)
        rel_sorted = jnp.take_along_axis(rel, order, axis=1)
        M = s.shape[1]
        cum_rel = jnp.cumsum(rel_sorted.astype(jnp.float32), axis=1)
        prec = cum_rel / jnp.arange(1, M + 1, dtype=jnp.float32)[None, :]
        results = []
        for k in self.eval_at:
            kk = min(k, M)
            ap_num = jnp.sum(jnp.where(rel_sorted[:, :kk], prec[:, :kk], 0.0),
                             axis=1)
            denom = jnp.minimum(jnp.sum(rel, axis=1).astype(jnp.float32),
                                float(kk))
            ap = jnp.where(denom > 0, ap_num / denom, 0.0)
            results.append(float(jnp.sum(ap * self._qweight)
                                 / jnp.sum(self._qweight)))
        return results


_METRIC_REGISTRY = {
    "l2": L2Metric, "mse": L2Metric, "mean_squared_error": L2Metric,
    "regression": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric,
    "l2_root": RMSEMetric,
    "l1": L1Metric, "mae": L1Metric, "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric, "mean_absolute_percentage_error": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric, "gamma-deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "cross_entropy": CrossEntropyMetric, "xentropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric, "kullback_leibler": KLDivMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "map": MAPMetric, "mean_average_precision": MAPMetric,
}

_DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2", "regression_l1": "l1", "huber": "huber",
    "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary_logloss", "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss", "lambdarank": "ndcg",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
}


def create_metrics(config: Config,
                   names: Optional[Sequence[str]] = None) -> List[Metric]:
    """Factory (reference metric.cpp:11-53); falls back to the
    objective's default metric when none requested."""
    names = list(names if names is not None else config.metric)
    if not names:
        default = _DEFAULT_METRIC_FOR_OBJECTIVE.get(config.objective)
        names = [default] if default else []
    out = []
    for nm in names:
        nm = nm.strip().lower()
        if nm in ("", "none", "null", "na"):
            continue
        cls = _METRIC_REGISTRY.get(nm)
        if cls is None:
            Log.warning(f"Unknown metric {nm}, ignored")
            continue
        out.append(cls(config))
    return out
