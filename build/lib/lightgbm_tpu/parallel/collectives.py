"""Collective-communication seam.

The functional equivalent of the reference's static Network class
(reference: include/LightGBM/network.h:86-296 — Allreduce,
ReduceScatter, Allgather, GlobalSyncUpByMin/Max/Mean, GlobalSum — and
the external-function injection point Network::Init(num_machines, rank,
reduce_scatter_fn, allgather_fn) at network.h:96 / c_api.h:760).

Inside jitted programs the collectives are implicit in shardings (see
parallel/mesh.py); this module exists for code that needs EXPLICIT
collective calls — the voting learner's vote exchange, distributed
objective syncs (RenewTreeOutput's GlobalSum, gbdt.cpp:795-804), and
tests that inject a fake backend the way LGBM_NetworkInitWithFunctions
allowed.  The default backend maps straight onto jax.lax collectives
over a named mesh axis; a host backend (numpy, single process) makes
the distributed code paths unit-testable without any devices.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Collectives:
    """Collective ops over a named mesh axis, usable inside shard_map."""

    def __init__(self, axis_name: Optional[str]):
        self.axis_name = axis_name

    @property
    def is_distributed(self) -> bool:
        return self.axis_name is not None

    # -- core three (the only ones the learners need; SURVEY §2.4) ----
    def allreduce_sum(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.psum(x, self.axis_name)

    def reduce_scatter(self, x, tiled_axis: int = 0):
        if self.axis_name is None:
            return x
        return jax.lax.psum_scatter(x, self.axis_name,
                                    scatter_dimension=tiled_axis,
                                    tiled=True)

    def all_gather(self, x, axis: int = 0):
        if self.axis_name is None:
            return x
        return jax.lax.all_gather(x, self.axis_name, axis=axis,
                                  tiled=True)

    # -- scalar sync helpers (network.h:165-257) ----------------------
    def global_sum(self, x):
        return self.allreduce_sum(x)

    def global_min(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.pmin(x, self.axis_name)

    def global_max(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.pmax(x, self.axis_name)

    def global_mean(self, x):
        if self.axis_name is None:
            return x
        return jax.lax.pmean(x, self.axis_name)

    def argmax_sync(self, value, payload):
        """Global argmax with payload broadcast — the
        SyncUpGlobalBestSplit pattern (parallel_tree_learner.h:184-207):
        every shard contributes (gain, split-struct); all shards end up
        with the payload of the globally best gain."""
        if self.axis_name is None:
            return payload
        gains = jax.lax.all_gather(value, self.axis_name)
        best = jnp.argmax(gains)
        gathered = jax.tree_util.tree_map(
            lambda p: jax.lax.all_gather(p, self.axis_name), payload)
        return jax.tree_util.tree_map(lambda g: g[best], gathered)

    def rank(self):
        if self.axis_name is None:
            return 0
        return jax.lax.axis_index(self.axis_name)

    def num_machines(self):
        if self.axis_name is None:
            return 1
        return jax.lax.axis_size(self.axis_name)


class HostCollectives(Collectives):
    """Single-process fake backend — the LGBM_NetworkInitWithFunctions
    analog for unit tests: simulates a k-way reduction by applying the
    reduction to caller-provided per-shard arrays."""

    def __init__(self, shards: int = 1):
        super().__init__(None)
        self.shards = shards

    def simulate_allreduce(self, per_shard_arrays):
        return np.sum(np.stack(per_shard_arrays), axis=0)

    def simulate_reduce_scatter(self, per_shard_arrays, axis: int = 0):
        total = self.simulate_allreduce(per_shard_arrays)
        return np.array_split(total, self.shards, axis=axis)

    def simulate_allgather(self, per_shard_arrays, axis: int = 0):
        return np.concatenate(per_shard_arrays, axis=axis)


class ExternalCollectives(HostCollectives):
    """User-injected reduce-scatter/allgather callables — the direct
    analog of LGBM_NetworkInitWithFunctions (reference c_api.h:760-762,
    network.h:96).  Callables receive and return numpy arrays; used by
    embedders that bring their own transport."""

    def __init__(self, num_machines: int, rank: int,
                 reduce_scatter_fn: Optional[Callable] = None,
                 allgather_fn: Optional[Callable] = None):
        super().__init__(shards=num_machines)
        self.external_rank = rank
        self.reduce_scatter_fn = reduce_scatter_fn
        self.allgather_fn = allgather_fn

    def simulate_reduce_scatter(self, per_shard_arrays, axis: int = 0):
        if self.reduce_scatter_fn is None:
            return super().simulate_reduce_scatter(per_shard_arrays, axis)
        return self.reduce_scatter_fn(per_shard_arrays)

    def simulate_allgather(self, per_shard_arrays, axis: int = 0):
        if self.allgather_fn is None:
            return super().simulate_allgather(per_shard_arrays, axis)
        return self.allgather_fn(per_shard_arrays)


_external: Optional[ExternalCollectives] = None


def install_external(num_machines: int, rank: int,
                     reduce_scatter_fn: Optional[Callable] = None,
                     allgather_fn: Optional[Callable] = None) -> None:
    """Install a process-global external backend (the
    LGBM_NetworkInitWithFunctions seam, exposed via capi.py)."""
    global _external
    _external = ExternalCollectives(num_machines, rank,
                                    reduce_scatter_fn, allgather_fn)


def external() -> Optional[ExternalCollectives]:
    return _external
