"""DART: Dropouts meet Multiple Additive Regression Trees.

Re-design of the reference DART (src/boosting/dart.hpp:26-201):
weight-proportional (or uniform) tree dropping before each gradient
computation, then the k/(k+1) (or xgboost-mode) renormalization of the
dropped trees.  Where the reference mutates model trees with Shrinkage
and replays AddScore, here tree contributions are recomputed on device
by traversing the HBM-resident bin matrix (ops/predict.py) and score
arrays are adjusted by weight deltas.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..utils.log import Log
from .gbdt import GBDT


class DART(GBDT):
    def __init__(self, config: Config, train_set: Dataset, **kwargs):
        super().__init__(config, train_set, **kwargs)
        self._drop_rng = np.random.RandomState(config.drop_seed)
        self.tree_weight: List[float] = []   # current weight per iteration
        self.sum_weight = 0.0
        self.drop_index: List[int] = []

    # ------------------------------------------------------------------
    def _before_boosting(self) -> None:
        self._dropping_trees()

    def _dropping_trees(self) -> None:
        """reference dart.hpp:86-136 DroppingTrees."""
        cfg = self.config
        self.drop_index = []
        is_skip = self._drop_rng.rand() < cfg.skip_drop
        if not is_skip and self.iter_ > 0:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                inv_avg = len(self.tree_weight) / max(self.sum_weight, 1e-30)
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate,
                                    cfg.max_drop * inv_avg
                                    / max(self.sum_weight, 1e-30))
                for i in range(self.iter_):
                    if self._drop_rng.rand() < \
                            drop_rate * self.tree_weight[i] * inv_avg:
                        self.drop_index.append(i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._drop_rng.rand() < drop_rate:
                        self.drop_index.append(i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
        # remove dropped trees' contribution from the training scores
        for i in self.drop_index:
            w = self.tree_weight[i]
            for k in range(self.num_class):
                t = self.device_trees[i * self.num_class + k]
                pred = self._predict_valid_fn(t, self.grower.bins)
                self.scores = self.scores.at[k].add(-w * pred)
        k_drop = len(self.drop_index)
        if not self.config.xgboost_dart_mode:
            self.shrinkage_rate = self.config.learning_rate / (1.0 + k_drop)
        else:
            self.shrinkage_rate = (self.config.learning_rate if k_drop == 0
                                   else self.config.learning_rate
                                   / (self.config.learning_rate + k_drop))

    # ------------------------------------------------------------------
    def _after_iteration(self) -> None:
        """Normalize dropped trees (reference dart.hpp:147-186) and
        record the new tree's weight."""
        cfg = self.config
        k = float(len(self.drop_index))
        for i in self.drop_index:
            w = self.tree_weight[i]
            if not cfg.xgboost_dart_mode:
                new_w = w * (k / (k + 1.0))
            else:
                new_w = w * (k / (k + cfg.learning_rate))
            for ki in range(self.num_class):
                idx = i * self.num_class + ki
                t = self.device_trees[idx]
                pred_train = self._predict_valid_fn(t, self.grower.bins)
                self.scores = self.scores.at[ki].add(new_w * pred_train)
                for vs in self.valid_sets:
                    pv = self._predict_valid_fn(t, vs.bins)
                    vs.scores = vs.scores.at[ki].add((new_w - w) * pv)
                # record the weight change; flush_models() bakes the
                # cumulative scale into the host tree lazily
                # (_scale_offset skips trees merged from an init_model)
                scale = new_w / w if w != 0 else 0.0
                self._tree_scale[self._scale_offset + idx] *= scale
            if not cfg.uniform_drop:
                self.sum_weight -= w * (1.0 / (k + 1.0)
                                        if not cfg.xgboost_dart_mode
                                        else 1.0 / (k + cfg.learning_rate))
                self.tree_weight[i] = new_w
            else:
                self.tree_weight[i] = new_w
        # record this iteration's tree weight (dart.hpp:60-64)
        self.tree_weight.append(self.shrinkage_rate)
        self.sum_weight += self.shrinkage_rate
