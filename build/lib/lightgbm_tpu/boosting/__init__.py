"""Boosting-mode factory (reference src/boosting/boosting.cpp:30-64)."""
from __future__ import annotations

from ..config import Config
from ..dataset import Dataset
from ..utils.log import Log
from .gbdt import GBDT


def create_boosting(config: Config, train_set: Dataset,
                    custom_objective: bool = False):
    bt = config.boosting_type
    if bt == "gbdt":
        return GBDT(config, train_set, custom_objective=custom_objective)
    if bt == "dart":
        from .dart import DART
        return DART(config, train_set, custom_objective=custom_objective)
    if bt == "goss":
        from .goss import GOSS
        return GOSS(config, train_set, custom_objective=custom_objective)
    if bt == "rf":
        from .rf import RF
        return RF(config, train_set, custom_objective=custom_objective)
    Log.fatal(f"Unknown boosting type {bt}")
