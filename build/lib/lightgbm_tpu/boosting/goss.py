"""GOSS: Gradient-based One-Side Sampling.

Re-design of the reference GOSS (src/boosting/goss.hpp:88-145): keep
the top ``top_rate`` rows by |g*h|, sample ``other_rate`` of the rest
and amplify their gradients by (1-a)/b.  The reference's per-thread
adaptive sequential sampling becomes a device top_k threshold plus an
i.i.d. Bernoulli draw — same marginal inclusion probabilities, fully
parallel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import Config
from ..dataset import Dataset
from .gbdt import GBDT


class GOSS(GBDT):
    def __init__(self, config: Config, train_set: Dataset, **kwargs):
        super().__init__(config, train_set, **kwargs)
        self._goss_key = jax.random.PRNGKey(config.bagging_seed + 1)
        self._goss_fn = jax.jit(self._goss_sample)

    def _bagging_counts(self, iteration: int):
        # GOSS replaces bagging entirely (reference goss.hpp Bagging)
        return self._full_counts, None

    def _use_bagging_fused(self) -> bool:
        return False

    def _sample_rows(self, g, h, counts):
        # no subsampling for the first 1/learning_rate iterations
        # (reference goss.hpp:138-140)
        if not self._sample_active():
            return g, h, counts
        self._goss_key, sub = jax.random.split(self._goss_key)
        return self._goss_fn(g, h, counts, sub)

    def _sample_active(self) -> bool:
        return self.iter_ >= int(1.0 / self.config.learning_rate)

    def _sample_rows_fused(self, g, h, counts, key):
        return self._goss_sample(g, h, counts, key)

    def _goss_sample(self, g, h, counts, key):
        n_real = self.num_data
        score = jnp.sum(jnp.abs(g * h), axis=0)          # (n_padded,)
        score = jnp.where(counts > 0, score, -jnp.inf)
        top_k = max(1, int(n_real * self.config.top_rate))
        other_k = max(1, int(n_real * self.config.other_rate))
        kth = jax.lax.top_k(score, top_k)[0][-1]
        is_top = score >= kth
        rest = (counts > 0) & ~is_top
        rest_cnt = jnp.maximum(jnp.sum(rest), 1)
        prob = other_k / rest_cnt
        u = jax.random.uniform(key, score.shape)
        sampled = rest & (u < prob)
        multiply = (n_real - top_k) / other_k
        keep = is_top | sampled
        scale = jnp.where(sampled, multiply, 1.0)[None, :]
        new_counts = jnp.where(keep, counts, 0.0)
        return g * scale, h * scale, new_counts
