"""TreeSHAP feature contributions.

Implements the polynomial-time TreeSHAP algorithm backing the
reference's PredictContrib (reference: include/LightGBM/tree.h:322-349
TreeSHAP/ExtendPath/UnwindPath, gbdt.cpp:670-689 PredictContrib):
per-node coverage fractions from internal_count, EXTEND/UNWIND over the
active decision path, output = per-feature contributions plus the
expected value in the last slot.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import Tree, K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, \
    _find_in_bitset


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, f=-1, z=1.0, o=1.0, w=1.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w

    def copy(self):
        return _PathElement(self.feature_index, self.zero_fraction,
                            self.one_fraction, self.pweight)


def _extend(path: List[_PathElement], zero_fraction, one_fraction,
            feature_index):
    path.append(_PathElement(feature_index, zero_fraction, one_fraction,
                             0.0 if len(path) > 0 else 1.0))
    depth = len(path) - 1
    for i in range(depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) \
            / (depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (depth - i) \
            / (depth + 1)


def _unwind(path: List[_PathElement], path_index):
    depth = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[depth].pweight
    for i in range(depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (depth + 1) \
                / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction \
                * (depth - i) / (depth + 1)
        else:
            path[i].pweight = path[i].pweight * (depth + 1) \
                / (zero_fraction * (depth - i))
    for i in range(path_index, depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_sum(path: List[_PathElement], path_index):
    depth = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[depth].pweight
    total = 0.0
    for i in range(depth - 1, -1, -1):
        if one_fraction != 0.0:
            tmp = next_one_portion * (depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction \
                * (depth - i) / (depth + 1)
        else:
            total += path[i].pweight / (zero_fraction * (depth - i)
                                        / (depth + 1))
    return total


def _decision(tree: Tree, node: int, x: np.ndarray) -> int:
    """Hot child of `node` for row x (mirrors tree.h Decision)."""
    dt = tree.decision_type[node]
    fval = x[tree.split_feature[node]]
    if dt & K_CATEGORICAL_MASK:
        if np.isnan(fval) or int(fval) < 0:
            return tree.right_child[node]
        ci = int(tree.threshold[node])
        lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
        words = np.asarray(tree.cat_threshold[lo:hi], dtype=np.uint32)
        if len(words) and _find_in_bitset(words,
                                          np.asarray([int(fval)]))[0]:
            return tree.left_child[node]
        return tree.right_child[node]
    mtype = (dt >> 2) & 3
    if np.isnan(fval) and mtype != 2:
        fval = 0.0
    is_zero = -1e-35 < fval <= 1e-35
    if (mtype == 1 and is_zero) or (mtype == 2 and np.isnan(fval)):
        return tree.left_child[node] if dt & K_DEFAULT_LEFT_MASK \
            else tree.right_child[node]
    return tree.left_child[node] if fval <= tree.threshold[node] \
        else tree.right_child[node]


def _node_count(tree: Tree, node: int) -> float:
    if node < 0:
        return max(float(tree.leaf_count[-node - 1]), 1.0)
    return max(float(tree.internal_count[node]), 1.0)


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               path: List[_PathElement], parent_zero: float,
               parent_one: float, parent_feature: int):
    path = [p.copy() for p in path]
    _extend(path, parent_zero, parent_one, parent_feature)
    if node < 0:   # leaf
        leaf = -node - 1
        for i in range(1, len(path)):
            w = _unwound_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction
                                          - el.zero_fraction) \
                * tree.leaf_value[leaf]
        return
    hot = _decision(tree, node, x)
    cold = tree.right_child[node] if hot == tree.left_child[node] \
        else tree.left_child[node]
    node_cnt = _node_count(tree, node)
    hot_frac = _node_count(tree, hot) / node_cnt
    cold_frac = _node_count(tree, cold) / node_cnt
    incoming_zero, incoming_one = 1.0, 1.0
    feat = int(tree.split_feature[node])
    path_index = next((i for i, el in enumerate(path)
                       if el.feature_index == feat), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind(path, path_index)
    _tree_shap(tree, x, phi, hot, path, hot_frac * incoming_zero,
               incoming_one, feat)
    _tree_shap(tree, x, phi, cold, path, cold_frac * incoming_zero, 0.0,
               feat)


def tree_expected_value(tree: Tree) -> float:
    counts = np.maximum(tree.leaf_count.astype(np.float64), 1.0)
    return float(np.average(tree.leaf_value, weights=counts))


def predict_contrib(booster, data: np.ndarray,
                    models: List[Tree]) -> np.ndarray:
    """SHAP contributions: (n, (F+1)) or (n, K*(F+1)) — last slot(s) are
    expected values (reference c_api predict_type=contrib layout)."""
    n = data.shape[0]
    F = booster.max_feature_idx + 1
    k = max(booster.num_tree_per_iteration, 1)
    out = np.zeros((n, k * (F + 1)), dtype=np.float64)
    for ti, tree in enumerate(models):
        cls = ti % k
        base = cls * (F + 1)
        if tree.num_leaves <= 1:
            out[:, base + F] += tree.leaf_value[0]
            continue
        ev = tree_expected_value(tree)
        out[:, base + F] += ev
        for r in range(n):
            phi = np.zeros(F + 1)
            _tree_shap(tree, data[r], phi, 0, [], 1.0, 1.0, -1)
            out[r, base:base + F] += phi[:F]
    return out[:, :F + 1] if k == 1 else out
