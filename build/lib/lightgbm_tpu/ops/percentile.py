"""Per-leaf (weighted) percentiles on device.

Implements the RenewTreeOutput leaf refit for L1-family objectives
(reference: regression_objective.hpp RenewTreeOutput + the
PercentileFun / WeightedPercentileFun templates in utils/common.h) as a
single lexicographic sort by (leaf, residual) followed by vectorized
segment interpolation — replacing the reference's per-leaf gather +
nth_element host loops.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def leaf_percentiles(residual: jax.Array, leaf_id: jax.Array,
                     num_leaves: int, alpha: float,
                     weights: Optional[jax.Array] = None) -> jax.Array:
    """alpha-percentile of ``residual`` within each leaf.

    Args:
      residual: (N,) values (label - prediction).
      leaf_id: (N,) int32; negative ids are ignored.
      num_leaves: static L.
      weights: optional (N,) weights (weighted-percentile semantics).

    Returns: (L,) f32 percentile per leaf (0 for empty leaves).
    """
    n = residual.shape[0]
    lid = jnp.where(leaf_id >= 0, leaf_id, num_leaves).astype(jnp.int32)
    if weights is None:
        s_leaf, s_r = jax.lax.sort((lid, residual), num_keys=2)
        starts = jnp.searchsorted(s_leaf, jnp.arange(num_leaves,
                                                     dtype=jnp.int32),
                                  side="left")
        ends = jnp.searchsorted(s_leaf, jnp.arange(num_leaves,
                                                   dtype=jnp.int32),
                                side="right")
        counts = ends - starts
        # PercentileFun: position interpolation at alpha*(n-1)
        pos = alpha * (counts - 1).astype(jnp.float32)
        lo = jnp.floor(pos).astype(jnp.int32)
        frac = pos - lo.astype(jnp.float32)
        i_lo = jnp.clip(starts + lo, 0, n - 1)
        i_hi = jnp.clip(starts + jnp.minimum(lo + 1, counts - 1), 0, n - 1)
        vals = s_r[i_lo] * (1.0 - frac) + s_r[i_hi] * frac
        return jnp.where(counts > 0, vals, 0.0)

    s_leaf, s_r, s_w = jax.lax.sort((lid, residual, weights), num_keys=2)
    arangeL = jnp.arange(num_leaves, dtype=jnp.int32)
    starts = jnp.searchsorted(s_leaf, arangeL, side="left")
    ends = jnp.searchsorted(s_leaf, arangeL, side="right")
    counts = ends - starts
    cumw = jnp.cumsum(s_w)
    cumw_before_start = jnp.where(starts > 0, cumw[jnp.maximum(starts - 1, 0)],
                                  0.0)
    total_w = jnp.where(counts > 0,
                        cumw[jnp.clip(ends - 1, 0, n - 1)]
                        - cumw_before_start, 0.0)
    # WeightedPercentileFun: c_i = cum_within - w_i/2, find first
    # c_i >= alpha * total, interpolate between neighbors
    safe_lid = jnp.clip(s_leaf, 0, num_leaves - 1)
    within = cumw - cumw_before_start[safe_lid]
    c = within - s_w / 2.0
    thr = alpha * total_w
    flag = (c >= thr[safe_lid]) & (s_leaf < num_leaves)
    idx_cand = jnp.where(flag, jnp.arange(n, dtype=jnp.int32), n)
    first = jax.ops.segment_min(idx_cand, safe_lid,
                                num_segments=num_leaves)
    first = jnp.where(counts > 0, first, 0)
    at_start = first <= starts
    at_end = first >= ends
    i = jnp.clip(first, 0, n - 1)
    prev = jnp.clip(first - 1, 0, n - 1)
    c_i = c[i]
    c_prev = c[prev]
    t = (thr - c_prev) / jnp.maximum(c_i - c_prev, 1e-30)
    interp = s_r[prev] * (1.0 - t) + s_r[i] * t
    vals = jnp.where(at_start, s_r[jnp.clip(starts, 0, n - 1)],
                     jnp.where(at_end,
                               s_r[jnp.clip(ends - 1, 0, n - 1)], interp))
    return jnp.where(counts > 0, vals, 0.0)
