// Native data-loading runtime: multithreaded CSV/TSV parsing.
//
// TPU-native counterpart of the reference's C++ IO layer
// (reference: src/io/parser.cpp CSV/TSV parsers + utils/text_reader.h
// buffered line reading + pipeline_reader.h double buffering).  The
// hot loop is a branch-light strtod-style float scan; rows are split
// across a thread pool after a newline-index pre-pass, writing
// directly into one contiguous row-major double buffer handed to
// Python via ctypes (no pybind11 dependency).
//
// Exports (C ABI):
//   ltpu_load_csv(path, sep, skip_rows, &rows, &cols) -> double* | null
//   ltpu_free(ptr)
//   ltpu_count_lines(path) -> long
//   ltpu_bin_values(values, n, bounds, nb, missing_type, out_bins)

#include <atomic>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// fast double parse: handles [+-]digits[.digits][eE[+-]digits], na/nan
// (a reduced strtod for the numeric-table hot path; falls back to
// strtod for anything exotic)
inline const char* parse_double(const char* p, double* out) {
  while (*p == ' ' || *p == '\t') ++p;
  const char* start = p;
  bool neg = false;
  if (*p == '-') { neg = true; ++p; }
  else if (*p == '+') ++p;
  if ((p[0] == 'n' || p[0] == 'N') && (p[1] == 'a' || p[1] == 'A')) {
    *out = std::nan("");
    p += 2;
    if (*p == 'n' || *p == 'N') ++p;
    return p;
  }
  double value = 0.0;
  int digits = 0;
  while (*p >= '0' && *p <= '9') {
    value = value * 10.0 + (*p - '0');
    ++p; ++digits;
  }
  if (*p == '.') {
    ++p;
    double frac = 0.1;
    while (*p >= '0' && *p <= '9') {
      value += (*p - '0') * frac;
      frac *= 0.1;
      ++p; ++digits;
    }
  }
  if (digits == 0) {  // not a plain number: strtod fallback
    char* end = nullptr;
    *out = std::strtod(start, &end);
    if (end == start) { *out = std::nan(""); ++p; return p; }
    return end;
  }
  if (*p == 'e' || *p == 'E') {
    ++p;
    bool eneg = false;
    if (*p == '-') { eneg = true; ++p; }
    else if (*p == '+') ++p;
    int ex = 0;
    while (*p >= '0' && *p <= '9') { ex = ex * 10 + (*p - '0'); ++p; }
    value *= std::pow(10.0, eneg ? -ex : ex);
  }
  *out = neg ? -value : value;
  return p;
}

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
  bool read(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (sz < 0) { std::fclose(f); return false; }
    data = static_cast<char*>(std::malloc(sz + 1));
    if (!data) { std::fclose(f); return false; }
    size = std::fread(data, 1, sz, f);
    data[size] = '\0';
    std::fclose(f);
    return true;
  }
};

}  // namespace

extern "C" {

long ltpu_count_lines(const char* path) {
  FileBuf buf;
  if (!buf.read(path)) return -1;
  long n = 0;
  for (size_t i = 0; i < buf.size; ++i) {
    if (buf.data[i] == '\n') ++n;
  }
  if (buf.size > 0 && buf.data[buf.size - 1] != '\n') ++n;
  return n;
}

// Parse a CSV/TSV file of floats into a freshly-malloc'd row-major
// (rows x cols) double array.  Returns nullptr on error.
double* ltpu_load_csv(const char* path, char sep, int skip_rows,
                      int64_t* out_rows, int64_t* out_cols) {
  FileBuf buf;
  if (!buf.read(path)) return nullptr;
  char* data = buf.data;
  size_t size = buf.size;

  // line-start index pre-pass
  std::vector<size_t> line_starts;
  line_starts.push_back(0);
  for (size_t i = 0; i < size; ++i) {
    if (data[i] == '\n' && i + 1 < size) line_starts.push_back(i + 1);
  }
  // drop trailing blank lines
  while (!line_starts.empty()) {
    size_t s = line_starts.back();
    bool blank = true;
    for (size_t i = s; i < size && data[i] != '\n'; ++i) {
      if (!std::isspace(static_cast<unsigned char>(data[i]))) {
        blank = false;
        break;
      }
    }
    if (blank) line_starts.pop_back(); else break;
  }
  if (static_cast<size_t>(skip_rows) >= line_starts.size()) return nullptr;
  size_t first = static_cast<size_t>(skip_rows);
  int64_t rows = static_cast<int64_t>(line_starts.size() - first);

  // column count from the first data row
  int64_t cols = 1;
  for (size_t i = line_starts[first]; i < size && data[i] != '\n'; ++i) {
    if (data[i] == sep) ++cols;
  }

  double* out = static_cast<double*>(
      std::malloc(sizeof(double) * rows * cols));
  if (!out) return nullptr;

  int nthreads = static_cast<int>(std::thread::hardware_concurrency());
  if (nthreads < 1) nthreads = 1;
  if (rows < nthreads * 64) nthreads = 1;
  std::atomic<bool> ok{true};

  auto worker = [&](int64_t r0, int64_t r1) {
    for (int64_t r = r0; r < r1; ++r) {
      const char* p = data + line_starts[first + r];
      double* row = out + r * cols;
      for (int64_t c = 0; c < cols; ++c) {
        double v = std::nan("");
        if (*p != sep && *p != '\n' && *p != '\r' && *p != '\0') {
          p = parse_double(p, &v);
        }
        row[c] = v;
        while (*p != sep && *p != '\n' && *p != '\0') ++p;
        if (*p == sep) ++p;
      }
    }
  };

  if (nthreads == 1) {
    worker(0, rows);
  } else {
    std::vector<std::thread> pool;
    int64_t per = (rows + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      int64_t r0 = t * per;
      int64_t r1 = r0 + per < rows ? r0 + per : rows;
      if (r0 >= r1) break;
      pool.emplace_back(worker, r0, r1);
    }
    for (auto& th : pool) th.join();
  }
  if (!ok.load()) { std::free(out); return nullptr; }
  *out_rows = rows;
  *out_cols = cols;
  return out;
}

void ltpu_free(double* ptr) { std::free(ptr); }

// Batch value->bin for one numerical feature (the reference's
// ValueToBin binary search, bin.h:450-486, vectorized + threaded).
void ltpu_bin_values(const double* values, int64_t n,
                     const double* bounds, int32_t num_bin,
                     int32_t missing_type, uint8_t* out_bins) {
  const int32_t search_n =
      missing_type == 2 ? num_bin - 1 : num_bin;  // 2 = NaN type
  auto one = [&](int64_t i) {
    double v = values[i];
    if (std::isnan(v)) {
      if (missing_type == 2) {
        out_bins[i] = static_cast<uint8_t>(num_bin - 1);
        return;
      }
      v = 0.0;
    }
    int32_t lo = 0, hi = search_n - 1;
    while (lo < hi) {
      int32_t mid = (lo + hi - 1) / 2;
      if (v <= bounds[mid]) hi = mid; else lo = mid + 1;
    }
    out_bins[i] = static_cast<uint8_t>(lo);
  };
  int nthreads = static_cast<int>(std::thread::hardware_concurrency());
  if (nthreads < 1 || n < 1 << 16) nthreads = 1;
  if (nthreads == 1) {
    for (int64_t i = 0; i < n; ++i) one(i);
  } else {
    std::vector<std::thread> pool;
    int64_t per = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      int64_t i0 = t * per;
      int64_t i1 = i0 + per < n ? i0 + per : n;
      if (i0 >= i1) break;
      pool.emplace_back([&, i0, i1]() {
        for (int64_t i = i0; i < i1; ++i) one(i);
      });
    }
    for (auto& th : pool) th.join();
  }
}

}  // extern "C"
