/*
 * lightgbm_tpu C API — native embedding surface for non-Python hosts.
 *
 * Plays the role of the reference's flat C API
 * (reference: include/LightGBM/c_api.h, src/c_api.cpp) with the same
 * function names, handle discipline and 0/-1 + LGBM_GetLastError error
 * convention (reference c_api.h:765-788).  The stack is inverted
 * relative to the reference: the core is a Python/JAX program, so this
 * library embeds CPython (statically linked against libpython) and
 * forwards each call to lightgbm_tpu.capi.  R's .Call shim or a Java
 * JNI wrapper links against this exactly the way the reference's
 * R-package/src/lightgbm_R.cpp links against lib_lightgbm.
 *
 * Threading: every entry point acquires the GIL; concurrent calls from
 * multiple host threads serialize (the reference serializes Booster
 * mutations with a std::mutex, c_api.cpp:67,311 — same effective
 * discipline).
 *
 * Environment: the embedded interpreter must be able to import
 * `lightgbm_tpu` (set PYTHONPATH, or call LTPU_AddSysPath first).
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* DatasetHandle;
typedef void* BoosterHandle;

/* dtype codes (reference c_api.h:33-41) */
#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32   (2)
#define C_API_DTYPE_INT64   (3)

/* predict task codes (reference c_api.h:43-47) */
#define C_API_PREDICT_NORMAL     (0)
#define C_API_PREDICT_RAW_SCORE  (1)
#define C_API_PREDICT_LEAF_INDEX (2)
#define C_API_PREDICT_CONTRIB    (3)

/* ---- embedding helpers (no reference analog; interpreter control) */
/* Append a directory to the embedded interpreter's sys.path BEFORE the
 * first API call (so `import lightgbm_tpu` resolves). */
int LTPU_AddSysPath(const char* path);
/* Force interpreter + module initialization now (otherwise lazy). */
int LTPU_EnsureInitialized(void);

/* ---- error handling */
const char* LGBM_GetLastError(void);

/* ---- Dataset */
int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out);
int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);
int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type);
/* out_ptr stays valid until the next GetField on the same handle or
 * DatasetFree (the reference returns a pointer into the Dataset too). */
int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr, int* out_type);
int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);
int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);
int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename);
int LGBM_DatasetFree(DatasetHandle handle);

/* ---- Booster */
int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters, BoosterHandle* out);
int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterFree(BoosterHandle handle);
int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data);
int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);
int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);
int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int64_t num_elements,
                                    int* is_finished);
int LGBM_BoosterRollbackOneIter(BoosterHandle handle);
int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration);
/* Number of metric values per dataset — size the GetEval buffer with
 * this first (reference c_api.h:430-437). */
int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len);
int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results);
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);
int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename);
int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);
int LGBM_BoosterDumpModel(BoosterHandle handle, int num_iteration,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str);
int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results);

/* ---- Network (reference c_api.h:749-762; see capi.py for the TPU
 * semantics — rendezvous goes through jax.distributed, these warn) */
int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines);
int LGBM_NetworkFree(void);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
