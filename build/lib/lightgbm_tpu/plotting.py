"""Plotting utilities (reference: python-package/lightgbm/plotting.py:
plot_importance :22, plot_metric :131, plot_tree/create_tree_digraph
:387).  matplotlib/graphviz are optional imports, mirroring the
reference's compat gating.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .booster import Booster
from .utils.log import Log


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, grid=True, **kwargs):
    plt = _check_matplotlib()
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    importance = booster.feature_importance(importance_type)
    names = booster.feature_names
    tuples = sorted(zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("cannot plot importance; no nonzero importances")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x) if importance_type == "split"
                              else round(x, 2)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster_or_record, metric: Optional[str] = None,
                dataset_names=None, ax=None, xlim=None, ylim=None,
                title="Metric during training", xlabel="Iterations",
                ylabel="auto", figsize=None, grid=True):
    plt = _check_matplotlib()
    if isinstance(booster_or_record, dict):
        eval_results = booster_or_record
    elif hasattr(booster_or_record, "evals_result_"):
        eval_results = booster_or_record.evals_result_
    else:
        raise TypeError("booster_or_record must be a dict of eval results "
                        "or a fitted LGBMModel")
    if not eval_results:
        raise ValueError("eval results are empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    names = dataset_names or list(eval_results.keys())
    metric_name = metric
    for name in names:
        metrics = eval_results[name]
        if metric_name is None:
            metric_name = next(iter(metrics))
        if metric_name not in metrics:
            continue
        values = metrics[metric_name]
        ax.plot(range(1, len(values) + 1), values, label=name)
    ax.legend(loc="best")
    if title:
        ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(metric_name if ylabel == "auto" else ylabel)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        name=None, comment=None, **kwargs):
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree")
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if getattr(booster, 'gbdt', None) is not None:
        booster._sync_models()
    if tree_index >= len(booster.models):
        raise IndexError("tree_index is out of range")
    tree = booster.models[tree_index]
    show_info = show_info or []
    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if node < 0:
            leaf = -node - 1
            name_ = f"leaf{leaf}"
            label = f"leaf {leaf}: {tree.leaf_value[leaf]:g}"
            if "leaf_count" in show_info:
                label += f"\ncount: {tree.leaf_count[leaf]}"
            graph.node(name_, label=label)
        else:
            name_ = f"split{node}"
            feat = tree.split_feature[node]
            fname = (booster.feature_names[feat]
                     if feat < len(booster.feature_names)
                     else f"Column_{feat}")
            label = f"{fname}"
            if tree.decision_type[node] & 1:
                label += " in categories"
            else:
                label += f" <= {tree.threshold[node]:g}"
            if "split_gain" in show_info:
                label += f"\ngain: {tree.split_gain[node]:g}"
            if "internal_count" in show_info:
                label += f"\ncount: {tree.internal_count[node]}"
            graph.node(name_, label=label)
            add(tree.left_child[node], name_, "yes")
            add(tree.right_child[node], name_, "no")
        if parent is not None:
            graph.edge(parent, name_, decision)

    add(0 if tree.num_leaves > 1 else -1)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info=None, **kwargs):
    plt = _check_matplotlib()
    try:
        import io
        from PIL import Image
    except ImportError:
        raise ImportError("You must install PIL to plot tree")
    graph = create_tree_digraph(booster, tree_index, show_info, **kwargs)
    s = graph.pipe(format="png")
    img = Image.open(io.BytesIO(s))
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ax.imshow(img)
    ax.axis("off")
    return ax
