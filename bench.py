"""Benchmark: Higgs-like binary training throughput on one chip.

Prints ONE JSON line.  Top-level fields describe the primary (1M-row)
point; ``scales`` carries BOTH measured scales — the 1M iteration
point and the HIGGS-true-scale 10.5M point (the round-2 verdict:
the headline regime must be proven at the baseline's actual scale,
where the resident one-hot only fits HBM because of the sub-byte
packing; docs/ROOFLINE.md).

Speed without an accuracy gate is not evidence: the quantized path's
held-out AUC is measured against the f32 path at the primary scale and
must stay within 1e-3 (the reference's own GPU-vs-CPU tolerance,
docs/GPU-Performance.rst:136-161).

Baseline derivation (BASELINE.md): the reference trains HIGGS
(10.5M rows x 28 features, 500 iters, 255 leaves) in 238.51 s on a
2x E5-2670v3 — 4.543e-8 s per (tree x row).  Each scale trains a
synthetic 28-feature binary task with the GPU-table config (63 bins,
255 leaves — docs/GPU-Performance.rst:108); vs_baseline =
scaled_reference_time / ours (>1 means faster than the reference CPU).

Honest economics: ``value`` is the warm per-tree extrapolation;
``prep_s``/``compile_s``/``cold_total_s`` are what a cold run pays.

Env knobs: BENCH_ROWS/BENCH_ITERS (primary), BENCH_ROWS_BIG/
BENCH_ITERS_BIG (big scale; BENCH_BIG=0 disables), BENCH_SKIP_F32=1
skips the f32 accuracy rerun, BENCH_PARAMS='{...}' overrides params,
BENCH_LEAVES/BENCH_MAX_BIN shrink the tree shape (smoke runs).
Serving bench knobs (BENCH_PREDICT=0 disables the predict scale):
BENCH_PREDICT_TRAIN_ROWS/BENCH_PREDICT_ITERS shape the served model,
BENCH_PREDICT_ROWS the bulk-throughput batch,
BENCH_PREDICT_SMALL_BATCH/BENCH_PREDICT_CALLS the p50 micro-batch
loop, BENCH_PREDICT_ANCHOR_ROWS the reference task=predict anchor.
Construction bench knobs (round 11; BENCH_CONSTRUCT=0 disables):
BENCH_CONSTRUCT_ROWS sizes the cold-construct point (default
min(BENCH_ROWS, 1M)); BENCH_LOCAL_REF_CONSTRUCT=0 skips just the
reference CSV-load anchor.
Local-reference knobs: BENCH_LOCAL_REF=0 disables all same-machine
reference runs; BENCH_LOCAL_REF_BIG=0 / BENCH_LOCAL_REF_LTR=0 /
BENCH_LOCAL_REF_PREDICT=0 disable just the 10.5M / lambdarank /
task=predict anchors (each costs minutes of 1-core CSV write +
reference wall-clock); BENCH_REF_ITERS / BENCH_REF_ITERS_BIG /
BENCH_REF_ITERS_LTR set the differenced iteration counts (defaults
30/10/10).

Budget discipline (round-5 verdict weak #1/#3: the r5 bench blew the
driver's wall-clock limit re-measuring fixed-binary anchors and died
with rc=124 before its own NDCG gate ran): BENCH_BUDGET_S (default
900) is a TOTAL wall-clock budget.  Local-reference anchors are
measured ONCE per (task, scale, params, data-seed, threads) and
persisted to the checked-in LOCAL_REF.json; later invocations reuse
the record instead of re-running the single-threaded reference binary.
An anchor that must run fresh is time-boxed to the remaining budget
minus a finishing reserve and skipped WITH A NOTE in the JSON on
overrun — the bench itself always completes with rc 0.
BENCH_LOCAL_REF_REFRESH=1 forces re-measurement.

Round-8 extension: the budget now bounds EVERY phase, not just the
anchors (the r5 rc=124 record — BENCH_r05.json `parsed: null` — came
from the 10.5M lightgbm_tpu MEASUREMENT run itself blowing the outer
driver timeout after the anchors were budgeted).  Each optional scale
is admitted against the measured primary-scale wall: the big scale is
scaled DOWN to rows that fit the remaining budget (with a
`scaled_down_from` note) or skipped with a note; the lambdarank and
predict scales skip with a note when their estimate doesn't fit.  The
JSON is always emitted and overruns never exit rc != 0 (quality gates
— AUC drift, NDCG floor, predict parity — still do).
"""
import gc
import json
import os
import sys
import time

import numpy as np

BENCH_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
BENCH_FEATURES = 28
BENCH_ITERS = int(os.environ.get("BENCH_ITERS", 100))
BENCH_ROWS_BIG = int(os.environ.get("BENCH_ROWS_BIG", 10_500_000))
BENCH_ITERS_BIG = int(os.environ.get("BENCH_ITERS_BIG", 100))
VALID_ROWS = int(os.environ.get("BENCH_VALID_ROWS", 200_000))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_MAX_BIN", 63))
REF_SEC_PER_TREE_ROW = 238.51 / (500 * 10_500_000)

BENCH_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 900))
# wall-clock reserved for the bench's own remaining work after any
# fresh anchor run (the finishing reserve a time-boxed anchor must
# not eat into)
ANCHOR_RESERVE_S = float(os.environ.get("BENCH_ANCHOR_RESERVE_S", 120))
# wall-clock reserved for emitting the JSON + diagnostics after the
# last admitted phase (round 8; hoisted to module scope in round 13
# so the primary admission can read it too)
FINISH_RESERVE_S = float(os.environ.get("BENCH_FINISH_RESERVE_S", 60))
_T0 = time.time()

LOCAL_REF_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "LOCAL_REF.json")


# measured axon-tunnel host round trip (docs/ROOFLINE.md r5) — the
# dispatch cost a remote-attached chunk amortizes; used to report what
# dispatch_chunk=auto WOULD pick on such a host from this run's slope
AXON_DISPATCH_S = 0.22


def budget_left() -> float:
    return BENCH_BUDGET_S - (time.time() - _T0)


def _host_tag() -> str:
    """Coarse host-hardware identity for anchor keys: the anchor is a
    SAME-MACHINE measurement, so a record must not be served to a
    different CPU (same-model hosts — e.g. the same chip-host across
    container restarts — correctly share)."""
    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.lower().startswith("model name"):
                    model = ln.split(":", 1)[1].strip()
                    break
    except OSError:
        import platform
        model = platform.processor() or platform.machine()
    return "".join(c if c.isalnum() else "_" for c in model)[:48] or "cpu"


def _local_ref_key(task, rows, iters, seed, params, threads) -> str:
    """Anchor cache key: the reference binary is fixed, so a record is
    valid as long as (task shape, generated data, training params,
    thread count, host CPU model) match."""
    return (f"{task}:rows={rows}:iters={iters}:seed={seed}"
            f":nl={params['num_leaves']}:mb={params['max_bin']}"
            f":lr={params['learning_rate']}"
            f":mdl={params['min_data_in_leaf']}"
            f":msh={params['min_sum_hessian_in_leaf']}"
            f":threads={threads}:host={_host_tag()}")


def _local_ref_load() -> dict:
    try:
        with open(LOCAL_REF_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


_EXPECTED_KEY_FIELDS = frozenset(
    ("rows", "iters", "seed", "nl", "mb", "lr", "mdl", "msh",
     "threads", "host"))
_REQUIRED_RECORD_FIELDS = ("per_tree_ms", "threads", "iters")
# task=predict anchors time the reference's batch scorer, not
# training: rows/s replaces per-tree time and no quality metric rides
# along (the parity gate lives in the lightgbm_tpu predict scale)
_REQUIRED_PREDICT_FIELDS = ("rows_per_s", "threads", "iters")
# task=construct anchors time the reference binary's load+bin of the
# same CSV (a num_iterations=1 run — dataset construction dominates);
# no quality metric rides along, parity is gated on the lightgbm_tpu
# side by byte-equality between its own construction paths
_REQUIRED_CONSTRUCT_FIELDS = ("construct_s", "threads", "iters")
_LOCAL_REF_NOTES: list = []
_LOCAL_REF_BAD: set = set()


def validate_local_ref():
    """Anchor-cache validation at bench startup (round 7): every
    LOCAL_REF.json record's key must parse into exactly the CURRENT
    key field set (_local_ref_key) and its payload must carry the
    schema the ratios read — a record written by an older/newer key
    format, or measured on a different host CPU, emits a skip-note
    instead of silently anchoring this run.  Returns
    (notes, bad_keys); bad keys are never served."""
    data = _local_ref_load()
    notes, bad = [], set()
    host = _host_tag()
    for key, rec in data.items():
        if key == "_schema":          # documentation entry, not a record
            continue
        parts = str(key).split(":")
        if parts[0] == "bench_wall":
            # round-13 primary-admission record (this bench's OWN
            # measured wall on this host, not a reference anchor):
            # its key is bench_wall:host=<tag> and its payload the
            # per-(row*iter) unit — own schema, own validation
            fields = dict(p.split("=", 1) for p in parts[1:]
                          if "=" in p)
            if set(fields) != {"host", "nl", "mb"} \
                    or not isinstance(rec, dict) \
                    or "unit_s_per_row_iter" not in rec:
                notes.append(f"bench_wall record {key!r}: schema "
                             "drift — record ignored")
                bad.add(key)
            continue
        fields = {}
        ok_parse = len(parts) >= 2
        for p in parts[1:]:
            if "=" not in p:
                ok_parse = False
                break
            k, v = p.split("=", 1)
            fields[k] = v
        if not ok_parse or set(fields) != _EXPECTED_KEY_FIELDS:
            missing = sorted(_EXPECTED_KEY_FIELDS - set(fields))
            extra = sorted(set(fields) - _EXPECTED_KEY_FIELDS)
            notes.append(
                f"anchor key {key!r}: key-set drift (missing fields "
                f"{missing}, unexpected {extra}) — record ignored; "
                "re-measure with BENCH_LOCAL_REF_REFRESH=1")
            bad.add(key)
            continue
        if parts[0] == "predict":
            schema_ok = (isinstance(rec, dict)
                         and ("skipped" in rec
                              or all(f in rec
                                     for f in _REQUIRED_PREDICT_FIELDS)))
        elif parts[0] == "construct":
            schema_ok = (isinstance(rec, dict)
                         and ("skipped" in rec
                              or all(f in rec
                                     for f in
                                     _REQUIRED_CONSTRUCT_FIELDS)))
        else:
            schema_ok = (isinstance(rec, dict)
                         and ("skipped" in rec
                              or (all(f in rec
                                      for f in _REQUIRED_RECORD_FIELDS)
                                  and ("auc" in rec
                                       or "ndcg10" in rec))))
        if not schema_ok:
            notes.append(
                f"anchor {key!r}: record schema drift (expected "
                f"{list(_REQUIRED_RECORD_FIELDS)} + auc|ndcg10) — "
                "record ignored")
            bad.add(key)
            continue
        if fields["host"] != host:
            notes.append(
                f"anchor {key!r}: measured on host CPU "
                f"{fields['host']!r}, this host is {host!r} — kept "
                "for that host, cannot anchor this run")
    return notes, bad


def _local_ref_store(key: str, record: dict) -> None:
    data = _local_ref_load()
    data[key] = record
    try:
        with open(LOCAL_REF_PATH, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
    except OSError as e:  # read-only checkout: reuse still works
        print(f"could not persist local-ref anchor ({e})",
              file=sys.stderr)


def make_data(n, f, seed=7, w=None):
    """Synthetic binary task.  ``w`` (the concept) defaults to a draw
    from the same stream — pass the training run's w for a held-out
    sample of the SAME concept."""
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    if w is None:
        w = rng.randn(f) * (rng.rand(f) > 0.3)
    logit = X[:, :f] @ w + 0.5 * np.sin(3 * X[:, 0]) * X[:, 1]
    y = (logit + rng.logistic(size=n) > 0).astype(np.float32)
    return X.astype(np.float64), y, w


def auc_score(y, s):
    """Tie-aware AUC (numpy; rank-sum formulation)."""
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_s = s[order]
    n = len(s)
    i = 0
    while i < n:
        j = i
        while j + 1 < n and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    pos = y > 0
    np_ = pos.sum()
    nn = n - np_
    if np_ == 0 or nn == 0:
        return float("nan")
    return float((ranks[pos].sum() - np_ * (np_ + 1) / 2) / (np_ * nn))


def _bench_telemetry():
    """The bench consumes the RUNTIME telemetry counters instead of
    private timers (round-9 tentpole): ``train_chunk`` itself records
    host_dispatch_ms (time-to-return of the async enqueue) and — with
    the fence enabled — device_wait_ms, so the numbers printed here
    and the numbers a production run exports via ``telemetry=spans``
    come from ONE code path (docs/OBSERVABILITY.md, bench-vs-runtime
    equivalence).  Mode is only ever raised, never lowered, so a
    BENCH_PARAMS telemetry override survives."""
    from lightgbm_tpu.telemetry import TELEMETRY
    if not TELEMETRY.on:
        TELEMETRY.configure("counters")
    TELEMETRY.set_fence(True)
    return TELEMETRY


def timed_chunks(gbdt, iters, chunk):
    """Run the warm training loop in ``chunk``-sized fused dispatches
    with the wall clock SPLIT into host/dispatch time (how long each
    train_chunk call takes to RETURN — the async enqueue, which on a
    remote-attached chip carries the dispatch RPC) and device wait
    (the per-chunk fence up to the drain), both read from the
    telemetry counters train_chunk maintains.  The split is what
    tracks ROOFLINE headroom #3 (the ≈1-2 ms/tree host gap) as a
    series.  Returns the timing dict shared by every bench scale."""
    tm = _bench_telemetry()

    def counters():
        c = tm.counters()
        # iteration (not tree) count: per_tree/trees_total keep the
        # pre-r9 per-ITERATION denominator — trees_dispatched scales
        # by num_class and would shift the series on a multiclass scale
        return (c.get("host_dispatch_ms", 0.0),
                c.get("device_wait_ms", 0.0),
                c.get("iterations", 0))

    def drain():
        np.asarray(gbdt.scores[:, :8])

    t0 = time.time()
    gbdt.train_chunk(chunk)
    drain()
    compile_s = time.time() - t0
    n_chunks = max(1, (iters - chunk) // chunk)
    h0, d0, n0 = counters()
    t0 = time.time()
    for _ in range(n_chunks):
        gbdt.train_chunk(chunk)
    drain()
    steady_s = time.time() - t0
    h1, d1, n1 = counters()
    host_s = (h1 - h0) / 1e3
    device_s = (d1 - d0) / 1e3
    trees = (n1 - n0) or n_chunks * chunk
    return {
        "compile_s": compile_s,
        "steady_s": steady_s,
        "per_tree": steady_s / trees,
        "trees_total": trees + chunk,
        "host_dispatch_s": host_s,
        "device_wait_s": device_s,
        "host_ms_per_tree": host_s / trees * 1e3,
        "device_ms_per_tree": device_s / trees * 1e3,
    }


def chunk_slope_probe(gbdt, probes=(4, 16)):
    """Fit the per-iteration chunk-slope series the r6 diagnosis
    tracks, reported for BOTH this host's measured dispatch cost and
    the known axon-RPC cost (the on-chip dispatch_chunk=auto
    expectation).  Delegates to GBDT.tune_dispatch_chunk — the
    dispatch_chunk=auto implementation — so the bench reports exactly
    what auto would fit, including its compile-discard double pass,
    return-vs-drain split and early-stop handling.  Consumes 2·Σprobes
    real training iterations."""
    from lightgbm_tpu.boosting.gbdt import pick_dispatch_chunk

    chunk, info = gbdt.tune_dispatch_chunk(probes=probes)
    probe_ms = {str(c): round(t * 1e3, 3)
                for c, t in info.get("probe_per_tree_s", {}).items()}
    if info.get("stopped") or "slope_s_per_iter" not in info:
        return {"stopped": True, "probe_per_tree_ms": probe_ms}
    base, slope = info["base_s"], info["slope_s_per_iter"]
    return {
        "probe_per_tree_ms": probe_ms,
        "base_ms": round(base * 1e3, 3),
        "slope_ms_per_iter": round(slope * 1e3, 4),
        "host_dispatch_ms": round(info["dispatch_s"] * 1e3, 2),
        "auto_pick_local": chunk,
        "auto_pick_axon_rpc": pick_dispatch_chunk(base, slope,
                                                  AXON_DISPATCH_S),
    }


def train_timed(cfg_params, X, y, iters):
    """Train ``iters`` trees; returns (gbdt, cfg, dtrain, prep_s,
    timing dict — see timed_chunks)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    cfg = Config.from_params(cfg_params)
    t0 = time.time()
    dtrain = lgb.Dataset(X, label=y)
    core = dtrain.construct(cfg)
    prep_s = time.time() - t0
    gbdt = GBDT(cfg, core)

    chunk = max(1, min(int(os.environ.get("BENCH_CHUNK", 10)),
                       iters // 2))
    timing = timed_chunks(gbdt, iters, chunk)
    # the economics a first-time user actually pays: dataset prep +
    # first (compiling) chunk + the remaining chunks, as measured —
    # NOT the warm per-tree extrapolation the headline `value` reports
    timing["cold_total_s"] = prep_s + timing["compile_s"] \
        + timing["steady_s"]
    return gbdt, cfg, dtrain, prep_s, timing


def attach_timing(out: dict, timing: dict) -> dict:
    """Copy the host/device wall split (and the chunk-slope fit when
    the probe ran) from a timed_chunks dict into a scale record — the
    series ROOFLINE headroom #3 tracks.

    ``timing_source`` marks the round-9 semantics change for series
    continuity: the split now comes from the telemetry counters with a
    per-chunk device fence, so the steady wall is host + device with
    NO chunk overlap (the pre-r9 loop enqueued all chunks back-to-back
    and drained once, hiding host dispatch under device execution on a
    pipelined backend) — compare r9+ per_tree against r8 anchors with
    that in mind."""
    out["host_dispatch_ms_per_tree"] = round(
        timing["host_ms_per_tree"], 3)
    out["device_wait_ms_per_tree"] = round(
        timing["device_ms_per_tree"], 3)
    out["timing_source"] = "telemetry_fenced"
    if "chunk_slope" in timing:
        out["chunk_slope"] = timing["chunk_slope"]
    return out


def heldout_scores(gbdt, cfg, vbins_np):
    """Raw scores of the trained ensemble on a held-out binned matrix,
    computed on device AFTER timing (one scan per pending tree stack;
    packed-carry stacks unpack their byte records inside the scan)."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import (predict_binned,
                                          unpack_tree_records_device)

    g = gbdt.grower
    vbins = jnp.asarray(vbins_np)
    shrink = gbdt.shrinkage_rate

    def acc(total, tr):
        pv = predict_binned(tr, vbins, g.f_group, g.g2f_lut,
                            g.f_missing, g.f_default_bin, g.f_num_bin,
                            max_steps=cfg.num_leaves)
        return total + shrink * pv

    @jax.jit
    def acc_stack(total, stack):
        out, _ = jax.lax.scan(lambda c, tr: (acc(c, tr), None),
                              total, stack)
        return out

    @jax.jit
    def acc_recs(total, recs):
        def body(carry, rec):
            tr = unpack_tree_records_device(rec, cfg.num_leaves,
                                            g.max_feature_bin)
            return acc(carry, tr), None
        out, _ = jax.lax.scan(body, total, recs)
        return out

    total = jnp.full(vbins.shape[0], gbdt.init_score, jnp.float32)
    for p in gbdt._pending:
        assert p[0] in ("stack", "rstack"), "bench expects chunked training"
        if p[0] == "rstack":
            for k in range(p[1].shape[1]):
                total = acc_recs(total, p[1][:, k])
        else:
            for stack in p[1]:
                total = acc_stack(total, stack)
    return np.asarray(total)


REF_LTR_SEC_PER_TREE_ROW = 215.32 / (500 * 2_270_296)  # MS-LTR row,
# docs/Experiments.rst:108-145 (2,270,296 rows, 500 trees, 215.32 s)


def attach_local_ref(out, ref, per_tree):
    """Fold a run_local_reference record + measured ratio into a scale
    dict (shared by the flat scales and the lambdarank scale).  A
    skip record lands as ``local_ref_skipped`` so the JSON documents
    WHY the anchor is absent (budget box, missing binary, ...)."""
    if ref is None:
        return out
    if "skipped" in ref:
        out["local_ref_skipped"] = ref["skipped"]
        return out
    out["local_ref"] = ref
    out["vs_local_reference"] = round(
        (ref["per_tree_ms"] / 1e3) / per_tree, 3)
    return out


def make_ltr_data(n_queries, f=136, seed=11, docs_lo=60, docs_hi=180,
                  w=None):
    """Synthetic MS-LTR-shaped ranking task: variable-size queries,
    graded 0-4 relevance from a noisy latent score with a per-query
    offset (so ranking within queries is learnable but absolute scores
    are not)."""
    rng = np.random.RandomState(seed)
    sizes = rng.randint(docs_lo, docs_hi + 1, size=n_queries)
    n = int(sizes.sum())
    X = rng.randn(n, f).astype(np.float32)
    if w is None:
        w = (rng.randn(f) * (rng.rand(f) > 0.5)).astype(np.float32)
    latent = X @ w + np.repeat(rng.randn(n_queries) * 2.0, sizes) \
        + rng.randn(n).astype(np.float32) * 2.0
    # graded labels by global quantiles (MS-LTR-like skew toward 0)
    qs = np.quantile(latent, [0.55, 0.78, 0.90, 0.97])
    y = np.digitize(latent, qs).astype(np.float32)
    return X.astype(np.float64), y, sizes, w


def ndcg_at_k(y, s, sizes, k=10):
    """Mean NDCG@k over queries (gain 2^label - 1, log2 discounts)."""
    out = []
    start = 0
    for sz in sizes:
        yl = y[start:start + sz]
        sl = s[start:start + sz]
        start += sz
        kk = min(k, sz)
        order = np.argsort(-sl, kind="stable")[:kk]
        gains = 2.0 ** yl[order] - 1
        disc = 1.0 / np.log2(np.arange(2, kk + 2))
        dcg = float(np.sum(gains * disc))
        best = np.sort(yl)[::-1][:kk]
        idcg = float(np.sum((2.0 ** best - 1) * disc))
        out.append(dcg / idcg if idcg > 0 else 0.0)
    return float(np.mean(out))


def run_ltr_scale():
    """Lambdarank perf point at MS-LTR shape (round-3 verdict #8): the
    per-query pairwise kernels get a wall-clock number, gated on
    held-out NDCG@10 actually learning the synthetic concept."""
    import lightgbm_tpu as lgb

    n_queries = int(os.environ.get("BENCH_LTR_QUERIES", 18_900))
    iters = int(os.environ.get("BENCH_LTR_ITERS", 30))
    X, y, sizes, w = make_ltr_data(n_queries)
    Xv, yv, sizes_v, _ = make_ltr_data(2000, seed=12, w=w)
    rows = X.shape[0]

    params = {
        "objective": "lambdarank", "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN, "learning_rate": 0.1, "verbose": -1,
        "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0,
        "hist_compute_dtype": "bfloat16",
        "quantized_grad": os.environ.get("BENCH_QUANTIZED", "1") != "0",
    }
    extra = os.environ.get("BENCH_PARAMS")
    if extra:
        params.update(json.loads(extra))
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config
    cfg = Config.from_params(params)
    t0 = time.time()
    dtrain = lgb.Dataset(X, label=y, group=sizes)
    core = dtrain.construct(cfg)
    prep_s = time.time() - t0
    gbdt = GBDT(cfg, core)

    chunk = max(1, min(int(os.environ.get("BENCH_CHUNK", 10)),
                       iters // 2))
    timing = timed_chunks(gbdt, iters, chunk)
    compile_s = timing["compile_s"]
    per_tree = timing["per_tree"]
    iters = timing["trees_total"]       # trees actually trained

    vcore = lgb.Dataset(Xv, label=yv, group=sizes_v,
                        reference=dtrain).construct(cfg)
    scores = heldout_scores(gbdt, cfg, vcore.group_bins)
    ndcg = ndcg_at_k(yv, scores, sizes_v, k=10)
    ndcg0 = ndcg_at_k(yv, np.zeros_like(scores), sizes_v, k=10)
    if not (ndcg >= ndcg0 + 0.03):
        raise SystemExit(
            f"lambdarank NDCG@10 ({ndcg:.4f}) did not clear the "
            f"untrained baseline ({ndcg0:.4f}) — ranking gate failed")
    ref_scaled = REF_LTR_SEC_PER_TREE_ROW * rows * iters
    out = {
        "rows": rows, "iters": iters, "task": "lambdarank",
        "queries": n_queries,
        "value": round(per_tree * iters, 3),
        "vs_baseline": round(ref_scaled / (per_tree * iters), 3),
        "ndcg10": round(ndcg, 6), "ndcg10_untrained": round(ndcg0, 6),
        "prep_s": round(prep_s, 3), "compile_s": round(compile_s, 3),
        "per_tree_ms": round(per_tree * 1e3, 2),
    }
    attach_timing(out, timing)
    # measured same-machine anchor for the ranking point too (round-4
    # verdict #2: 1.49x rested entirely on the scaled denominator and
    # the NDCG gate was only vs-untrained — this runs the reference
    # binary with .query side files and records its NDCG@10 on the
    # same held-out draw)
    if os.environ.get("BENCH_LOCAL_REF_LTR", "1") != "0":
        # free the TPU training state before the minutes-long host-side
        # reference run (write_csv makes another full float64 copy)
        del gbdt, dtrain, vcore
        gc.collect()
        ref = run_local_reference(
            X, y, Xv, yv, params,
            int(os.environ.get("BENCH_REF_ITERS_LTR", 10)),
            group=sizes, group_valid=sizes_v, task="lambdarank",
            seed=11)
        attach_local_ref(out, ref, per_tree)
        # ranking-quality gate vs the SAME-DATA reference (round 5:
        # the weaker vs-untrained gate let deterministic int8 rounding
        # sit at 0.33 NDCG@10 while the reference scored 0.54 — this
        # gate would have caught it; ours trains 3x the iterations, so
        # matching the reference's 10-iter score is a floor, not a
        # bar).  The LOCAL_REF.json cache is what lets this gate
        # actually EXECUTE under the driver budget (r5 weak #3: the
        # gate was dead code because the anchor path always timed out)
        if ref is not None and "ndcg10" in ref:
            out["ndcg_gate"] = "pass" if ndcg >= ref["ndcg10"] else "fail"
            if ndcg < ref["ndcg10"]:
                raise SystemExit(
                    f"lambdarank NDCG@10 ({ndcg:.4f}) fell below the "
                    f"same-machine reference's ({ref['ndcg10']:.4f}) "
                    "on the identical draw — ranking quality gate "
                    "failed")
        else:
            out["ndcg_gate"] = "skipped (no local reference anchor)"
    else:
        out["ndcg_gate"] = "skipped (BENCH_LOCAL_REF_LTR=0)"
    return out


def run_local_reference(X, y, Xv, yv, params, iters,
                        group=None, group_valid=None, task="binary",
                        seed=7):
    """Train the ACTUAL reference CPU binary (.refbuild/lightgbm) on the
    SAME generated data on THIS machine (round-3 verdict #2: the scaled
    2013 Xeon number is an extrapolation; this is a measurement).

    The reference binary is FIXED, so each anchor is measured once and
    persisted to LOCAL_REF.json keyed by (task, scale, params,
    data-seed, threads); later invocations reuse the record (r5
    verdict weak #1: re-running the single-threaded binary every
    invocation blew the driver budget).  A fresh measurement is
    time-boxed to the remaining BENCH_BUDGET_S minus the finishing
    reserve; on overrun a ``{"skipped": reason}`` record documents the
    absence instead of killing the bench.

    Methodology: data goes through save_binary once (so CSV parsing is
    paid once), then per-tree time = (t(iters) - t(small)) /
    (iters - small) — the two-run differencing cancels binary-load +
    setup time.  ``group``/``group_valid`` (per-query doc counts) switch
    the held-out metric to NDCG@10 and emit the reference's ``.query``
    side files (src/io/metadata.cpp query loading).  Returns a dict with
    per_tree_ms, auc or ndcg10 (held-out), threads; a skip dict; or
    None when disabled (BENCH_LOCAL_REF=0) or iters is too small to
    difference."""
    import shutil
    import subprocess
    import tempfile

    ref_bin = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".refbuild", "lightgbm")
    small = max(2, iters // 10)
    if os.environ.get("BENCH_LOCAL_REF", "1") == "0" or iters <= small:
        return None
    threads = os.cpu_count() or 1
    key = _local_ref_key(task, X.shape[0], iters, seed, params, threads)
    if os.environ.get("BENCH_LOCAL_REF_REFRESH") != "1":
        cached = (None if key in _LOCAL_REF_BAD
                  else _local_ref_load().get(key))
        if cached is not None:
            print(f"local reference anchor reused from LOCAL_REF.json "
                  f"[{key}]", file=sys.stderr)
            return dict(cached, cached=True)
    if not os.path.exists(ref_bin):
        return {"skipped": "reference binary absent "
                           "(.refbuild/lightgbm)"}
    box = budget_left() - ANCHOR_RESERVE_S
    # the CSV serialization itself is unboxable once started (host-side
    # numpy/pandas write, ~2M cells/s single-core) — price it into the
    # admission check so a near-empty budget can't start a multi-minute
    # write that overshoots BENCH_BUDGET_S before the first time-boxed
    # subprocess even launches (the r5 rc=124 failure mode)
    est_csv_s = (X.size + X.shape[0] + Xv.size + Xv.shape[0]) / 2e6
    if box < 30 + est_csv_s:
        return {"skipped": f"insufficient budget for a fresh anchor "
                           f"({box:.0f}s left after reserve, CSV write "
                           f"alone est. {est_csv_s:.0f}s); set "
                           "BENCH_BUDGET_S higher or pre-seed "
                           "LOCAL_REF.json"}
    tmp = tempfile.mkdtemp(prefix="bench_ref_")

    def write_csv(path, label, feats):
        arr = np.column_stack([label, feats])
        try:
            import pandas as pd
            pd.DataFrame(arr).to_csv(path, header=False, index=False,
                                     float_format="%.8g")
        except ImportError:
            np.savetxt(path, arr, fmt="%.8g", delimiter=",")

    try:
        train_csv = os.path.join(tmp, "train.csv")
        valid_csv = os.path.join(tmp, "valid.csv")
        write_csv(train_csv, y, X)
        write_csv(valid_csv, yv, Xv)
        if group is not None:
            np.savetxt(train_csv + ".query", np.asarray(group, np.int64),
                       fmt="%d")
            np.savetxt(valid_csv + ".query",
                       np.asarray(group_valid, np.int64), fmt="%d")

        base = (f"task=train data={train_csv} objective={params['objective']}"
                f" num_leaves={params['num_leaves']}"
                f" max_bin={params['max_bin']}"
                f" learning_rate={params['learning_rate']}"
                f" min_data_in_leaf={params['min_data_in_leaf']}"
                f" min_sum_hessian_in_leaf={params['min_sum_hessian_in_leaf']}"
                f" num_threads={threads} verbose=-1").split()

        def run(extra):
            t0 = time.time()
            subprocess.run([ref_bin] + base + extra, check=True,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, cwd=tmp,
                           timeout=max(10.0,
                                       budget_left() - ANCHOR_RESERVE_S))
            return time.time() - t0

        # one-time binning + binary cache (excluded from timing)
        run(["num_iterations=1", "save_binary=true",
             f"output_model={tmp}/warm.txt"])
        base[1] = f"data={train_csv}.bin"
        t_small = run([f"num_iterations={small}",
                       f"output_model={tmp}/m_small.txt"])
        t_full = run([f"num_iterations={iters}",
                      f"output_model={tmp}/model.txt"])
        per_tree = (t_full - t_small) / (iters - small)

        # held-out metric of the reference model on the same valid draw
        pred_file = os.path.join(tmp, "preds.txt")
        subprocess.run(
            [ref_bin, "task=predict", f"data={valid_csv}",
             f"input_model={tmp}/model.txt",
             f"output_result={pred_file}", "verbose=-1"],
            check=True, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, cwd=tmp,
            timeout=max(10.0, budget_left() - ANCHOR_RESERVE_S))
        preds = np.loadtxt(pred_file)
        out = {"per_tree_ms": round(per_tree * 1e3, 2),
               "threads": threads,
               "train_s_measured": round(t_full, 3), "iters": iters}
        if group is not None:
            out["ndcg10"] = round(ndcg_at_k(yv, preds, group_valid, 10), 6)
        else:
            out["auc"] = round(auc_score(yv, preds), 6)
        _local_ref_store(key, out)
        return out
    except subprocess.TimeoutExpired:
        return {"skipped": "anchor run hit the BENCH_BUDGET_S time box;"
                           " re-run with a larger budget to seed "
                           "LOCAL_REF.json"}
    except Exception as e:  # a broken reference run must not discard
        # the completed TPU measurements
        print(f"local reference run failed ({type(e).__name__}: {e}); "
              "reporting scaled baseline only", file=sys.stderr)
        return {"skipped": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_local_reference_predict(model_str, X, y, params, n_trees,
                                seed=21):
    """Measure the reference CPU binary's ``task=predict`` on the SAME
    model text and data on THIS machine — the serving roofline's
    anchor.  Methodology: the model is our saved text (interchangeable
    format), predict wall is differenced between the full matrix and a
    1/8 prefix so binary-load + model-parse cancel; the per-row CSV
    parse does NOT cancel and is part of the reference CLI's serving
    cost (noted in the record).  Cached in LOCAL_REF.json under a
    ``predict:...`` key (same key fields; ``iters`` = model trees)."""
    import shutil
    import subprocess
    import tempfile

    ref_bin = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".refbuild", "lightgbm")
    if os.environ.get("BENCH_LOCAL_REF", "1") == "0" \
            or os.environ.get("BENCH_LOCAL_REF_PREDICT", "1") == "0":
        return None
    threads = os.cpu_count() or 1
    key = _local_ref_key("predict", X.shape[0], n_trees, seed, params,
                         threads)
    if os.environ.get("BENCH_LOCAL_REF_REFRESH") != "1":
        cached = (None if key in _LOCAL_REF_BAD
                  else _local_ref_load().get(key))
        if cached is not None:
            print(f"local predict anchor reused from LOCAL_REF.json "
                  f"[{key}]", file=sys.stderr)
            return dict(cached, cached=True)
    if not os.path.exists(ref_bin):
        return {"skipped": "reference binary absent "
                           "(.refbuild/lightgbm)"}
    box = budget_left() - ANCHOR_RESERVE_S
    est_csv_s = (X.size + X.shape[0]) / 2e6
    if box < 30 + est_csv_s:
        return {"skipped": f"insufficient budget for a fresh predict "
                           f"anchor ({box:.0f}s left after reserve, "
                           f"CSV write alone est. {est_csv_s:.0f}s)"}
    tmp = tempfile.mkdtemp(prefix="bench_refp_")
    try:
        n = X.shape[0]
        n_small = max(1, n // 8)
        full_csv = os.path.join(tmp, "full.csv")
        small_csv = os.path.join(tmp, "small.csv")
        arr = np.column_stack([y, X])
        try:
            import pandas as pd
            pd.DataFrame(arr).to_csv(full_csv, header=False, index=False,
                                     float_format="%.8g")
            pd.DataFrame(arr[:n_small]).to_csv(
                small_csv, header=False, index=False, float_format="%.8g")
        except ImportError:
            np.savetxt(full_csv, arr, fmt="%.8g", delimiter=",")
            np.savetxt(small_csv, arr[:n_small], fmt="%.8g",
                       delimiter=",")
        model_txt = os.path.join(tmp, "model.txt")
        with open(model_txt, "w") as f:
            f.write(model_str)

        def run_predict(data_csv):
            t0 = time.time()
            subprocess.run(
                [ref_bin, "task=predict", f"data={data_csv}",
                 f"input_model={model_txt}",
                 f"output_result={tmp}/preds.txt",
                 f"num_threads={threads}", "verbose=-1"],
                check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, cwd=tmp,
                timeout=max(10.0, budget_left() - ANCHOR_RESERVE_S))
            return time.time() - t0

        t_small = run_predict(small_csv)
        t_full = run_predict(full_csv)
        if t_full <= t_small:
            return {"skipped": "predict differencing degenerate "
                               f"(t_full {t_full:.3f}s <= t_small "
                               f"{t_small:.3f}s at n={n})"}
        out = {"rows_per_s": round((n - n_small) / (t_full - t_small)),
               "threads": threads, "iters": n_trees, "rows": n,
               "note": "differenced wall includes the reference CLI's "
                       "per-row CSV parse"}
        _local_ref_store(key, out)
        return out
    except subprocess.TimeoutExpired:
        return {"skipped": "predict anchor hit the BENCH_BUDGET_S time "
                           "box"}
    except Exception as e:
        print(f"local predict reference failed ({type(e).__name__}: "
              f"{e})", file=sys.stderr)
        return {"skipped": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_local_reference_construct(X, y, params, seed=31):
    """Time the reference CPU binary's dataset construction (text parse
    + bin-mapper fit + binning + binary-cache save) of the SAME CSV on
    THIS machine — the anchor for the round-11 ``construct`` block.  A
    ``num_iterations=1`` training run is construction-dominated (one
    31-leaf tree on an already-binned matrix is milliseconds); the one
    tree rides along in the record's note.  Cached in LOCAL_REF.json
    under a ``construct:...`` key (``iters`` = 1)."""
    import shutil
    import subprocess
    import tempfile

    ref_bin = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".refbuild", "lightgbm")
    if os.environ.get("BENCH_LOCAL_REF", "1") == "0" \
            or os.environ.get("BENCH_LOCAL_REF_CONSTRUCT", "1") == "0":
        return None
    threads = os.cpu_count() or 1
    key = _local_ref_key("construct", X.shape[0], 1, seed, params,
                         threads)
    if os.environ.get("BENCH_LOCAL_REF_REFRESH") != "1":
        cached = (None if key in _LOCAL_REF_BAD
                  else _local_ref_load().get(key))
        if cached is not None:
            print(f"local construct anchor reused from LOCAL_REF.json "
                  f"[{key}]", file=sys.stderr)
            return dict(cached, cached=True)
    if not os.path.exists(ref_bin):
        return {"skipped": "reference binary absent "
                           "(.refbuild/lightgbm)"}
    box = budget_left() - ANCHOR_RESERVE_S
    est_csv_s = (X.size + X.shape[0]) / 2e6
    if box < 30 + est_csv_s:
        return {"skipped": f"insufficient budget for a fresh construct "
                           f"anchor ({box:.0f}s left after reserve, "
                           f"CSV write alone est. {est_csv_s:.0f}s)"}
    tmp = tempfile.mkdtemp(prefix="bench_refc_")
    try:
        train_csv = os.path.join(tmp, "train.csv")
        arr = np.column_stack([y, X])
        try:
            import pandas as pd
            pd.DataFrame(arr).to_csv(train_csv, header=False,
                                     index=False, float_format="%.8g")
        except ImportError:
            np.savetxt(train_csv, arr, fmt="%.8g", delimiter=",")
        t0 = time.time()
        subprocess.run(
            [ref_bin, "task=train", f"data={train_csv}",
             f"objective={params['objective']}",
             f"num_leaves={params['num_leaves']}",
             f"max_bin={params['max_bin']}",
             "num_iterations=1", "save_binary=true",
             f"num_threads={threads}",
             f"output_model={tmp}/warm.txt", "verbose=-1"],
            check=True, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, cwd=tmp,
            timeout=max(10.0, budget_left() - ANCHOR_RESERVE_S))
        out = {"construct_s": round(time.time() - t0, 3),
               "threads": threads, "iters": 1, "rows": int(X.shape[0]),
               "note": "reference task=train num_iterations=1 "
                       "save_binary=true wall — CSV parse + bin fit + "
                       "binning + cache write (+ one tree)"}
        _local_ref_store(key, out)
        return out
    except subprocess.TimeoutExpired:
        return {"skipped": "construct anchor hit the BENCH_BUDGET_S "
                           "time box"}
    except Exception as e:
        print(f"local construct reference failed ({type(e).__name__}: "
              f"{e})", file=sys.stderr)
        return {"skipped": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_construct_scale(params):
    """Dataset-construction roofline point (round 11): cold-construct
    rows/s of the parallel pipeline (threaded mapper fit + native
    numerical/categorical/EFB binning) against the serial pure-Python
    baseline measured IN THE SAME RUN, thread scaling 1 vs auto, and
    the binary-cache v2 save/reload — gated on the packed matrix being
    byte-identical across every path.  On a 1-core host the thread
    scaling row reads ~1.0x by construction; the headline speedup is
    the compiled pipeline vs the Python loop either way."""
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.binning import resolve_construct_threads
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.dataset_io import load_binary, save_binary

    rows = int(os.environ.get("BENCH_CONSTRUCT_ROWS",
                              min(BENCH_ROWS, 1_000_000)))
    X, y, _ = make_data(rows, BENCH_FEATURES, seed=31)
    base = {"objective": "binary", "num_leaves": params["num_leaves"],
            "max_bin": params["max_bin"], "learning_rate": 0.1,
            "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0,
            "verbose": -1}

    def construct(**overrides):
        cfg = Config.from_params(dict(base, **overrides))
        t0 = time.time()
        core = lgb.Dataset(X, label=y).construct(cfg)
        return core, time.time() - t0

    # serial baseline FIRST (same run, same data): pure-Python mapper
    # fit + searchsorted binning, one thread — the pre-r6 pipeline
    core_serial, serial_s = construct(construct_threads=1,
                                      native_binning=False)
    core_cold, cold_s = construct()
    if not np.array_equal(np.asarray(core_serial.group_bins),
                          np.asarray(core_cold.group_bins)):
        raise SystemExit(
            "construct parity gate failed: the parallel/native "
            "pipeline's group_bins differ from the serial Python "
            "path's on the bench draw")
    del core_serial
    gc.collect()
    _, t1_s = construct(construct_threads=1)

    tmp = tempfile.mkdtemp(prefix="bench_construct_")
    try:
        bp = os.path.join(tmp, "train.bin")
        t0 = time.time()
        save_binary(core_cold, bp)
        save_s = time.time() - t0
        t0 = time.time()
        core_re = load_binary(bp)
        # touch the matrix so lazily-paged memmap IO is inside the
        # measurement, not deferred to the consumer
        checksum = int(np.asarray(core_re.group_bins[::
                                  max(1, rows // 4096)]).sum())
        reload_s = time.time() - t0
        if not np.array_equal(np.asarray(core_re.group_bins),
                              np.asarray(core_cold.group_bins)):
            raise SystemExit("binary-cache v2 reload parity gate "
                             "failed: reloaded group_bins differ")
        del core_re
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    del checksum

    out = {
        "task": "construct", "rows": rows, "features": BENCH_FEATURES,
        "cold_construct_s": round(cold_s, 3),
        "cold_rows_per_s": round(rows / max(cold_s, 1e-9)),
        "serial_construct_s": round(serial_s, 3),
        "serial_rows_per_s": round(rows / max(serial_s, 1e-9)),
        "speedup_vs_serial": round(serial_s / max(cold_s, 1e-9), 2),
        "threads_auto": resolve_construct_threads(None),
        "thread_scaling": {"1": round(t1_s, 3),
                           "auto": round(cold_s, 3),
                           "x": round(t1_s / max(cold_s, 1e-9), 2)},
        "cache_save_s": round(save_s, 3),
        "cache_reload_s": round(reload_s, 3),
        "reload_x_cold": round(cold_s / max(reload_s, 1e-9), 1),
        "parity": "pass",
    }
    ref = run_local_reference_construct(X, y, base)
    if ref is None:
        out["local_ref_skipped"] = "BENCH_LOCAL_REF[_CONSTRUCT]=0"
    elif "skipped" in ref:
        out["local_ref_skipped"] = ref["skipped"]
    else:
        out["local_ref"] = ref
        out["vs_local_reference"] = round(
            ref["construct_s"] / max(cold_s, 1e-9), 3)
    return out


def _rss_mb() -> float:
    """Current VmRSS in MB (/proc; 0.0 where unavailable) — the
    shard_construct block reports the resident-set DELTA of each
    construction route, the rows-per-chip signal sharding exists for."""
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return float(ln.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def run_shard_construct(params):
    """Sharded-construct roofline point (round 16, ROADMAP item 1):
    the mesh-sharded data plane measured against the single-matrix
    route on the same draw — per-shard construct rows/s, the
    distributed bin-find merge wall, resident-set delta per route —
    gated on the packed shards being byte-identical to the
    single-matrix construction and on a shard-cache v2 round trip
    (manifest world-size refusal included).  2 simulated participants
    by default (BENCH_SHARD_PARTICIPANTS); the
    order-of-magnitude-past-10.5M-rows series tracks the same keys in
    MULTICHIP_r*.json runs."""
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.sharded import (ShardCacheError, ShardedDataset,
                                      binfind, load_shard_cache,
                                      save_shard_cache)

    rows = int(os.environ.get("BENCH_SHARD_ROWS",
                              min(BENCH_ROWS, 500_000)))
    shards = int(os.environ.get("BENCH_SHARD_PARTICIPANTS", 2))
    X, y, _ = make_data(rows, BENCH_FEATURES, seed=41)
    base = {"objective": "binary", "num_leaves": params["num_leaves"],
            "max_bin": params["max_bin"], "verbose": -1}
    cfg = Config.from_params(base)

    gc.collect()
    rss0 = _rss_mb()
    t0 = time.time()
    single = lgb.Dataset(X, label=y).construct(cfg)
    single_s = time.time() - t0
    rss_single = max(0.0, _rss_mb() - rss0)

    # the merge wall on its own: candidates + instrumented allgather +
    # deterministic merge (the network-facing slice of construction)
    from lightgbm_tpu.sharded.dataset import shard_row_ranges
    ranges = shard_row_ranges(rows, shards)
    t0 = time.time()
    cands = [binfind.collect_candidates(X[a:b], cfg, rank=i,
                                        world=shards)
             for i, (a, b) in enumerate(ranges)]
    _vals, _rows_m, _tot = binfind.merge_candidates(cands)
    merge_wall_ms = (time.time() - t0) * 1e3
    del cands, _vals, _rows_m

    gc.collect()
    rss1 = _rss_mb()
    t0 = time.time()
    sds = ShardedDataset.construct_sharded(X, label=y, config=cfg,
                                           num_shards=shards)
    shard_s = time.time() - t0
    rss_sharded = max(0.0, _rss_mb() - rss1)

    if not np.array_equal(sds.assembled_group_bins(),
                          np.asarray(single.group_bins)):
        raise SystemExit(
            "shard_construct parity gate failed: sharded-route bins "
            "differ from the single-matrix construction")
    if binfind.mapper_fingerprint(sds.mappers, sds._bundles,
                                  sds.max_bin) \
            != binfind.mapper_fingerprint(single.mappers,
                                          single._bundles,
                                          single.max_bin):
        raise SystemExit("shard_construct mapper gate failed: merged "
                         "mappers differ from the single-host fit")

    tmp = tempfile.mkdtemp(prefix="bench_shard_")
    try:
        save_shard_cache(sds, tmp)
        t0 = time.time()
        re = load_shard_cache(tmp, expect_world_size=shards)
        reload_s = time.time() - t0
        if not np.array_equal(re.assembled_group_bins(),
                              sds.assembled_group_bins()):
            raise SystemExit("shard-cache v2 reload parity gate "
                             "failed")
        try:
            load_shard_cache(tmp, expect_world_size=shards + 1)
            raise SystemExit("shard-cache manifest accepted a wrong "
                             "world size")
        except ShardCacheError:
            manifest_reject = "pass"
        del re
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    per_shard_rows = rows / shards
    return {
        "task": "shard_construct", "rows": rows, "shards": shards,
        "features": BENCH_FEATURES,
        "shard_construct_s": round(shard_s, 3),
        "shard_rows_per_s": round(rows / max(shard_s, 1e-9)),
        "per_shard_rows_per_s": round(
            per_shard_rows / max(shard_s, 1e-9)),
        "single_construct_s": round(single_s, 3),
        "vs_single_matrix": round(single_s / max(shard_s, 1e-9), 2),
        "merge_wall_ms": round(merge_wall_ms, 2),
        "rss_single_mb": round(rss_single, 1),
        "rss_sharded_mb": round(rss_sharded, 1),
        "cache_reload_s": round(reload_s, 3),
        "parity": "pass",
        "manifest_reject": manifest_reject,
    }


_DIST_EXCHANGE_WORKER = r"""
import json, os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
leaves, groups, bins, reps = (int(a) for a in sys.argv[4:8])
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel import transport as T
from lightgbm_tpu.parallel.collectives import host_exchange_histograms
from lightgbm_tpu.telemetry import TELEMETRY
cfg = Config.from_params({"verbose": -1, "collective_transport": "tcp"})
tp = T.TcpTransport.create(coord, nproc, pid, config=cfg)
T.install(tp)
rng = np.random.RandomState(7 + pid)
hist = np.round(rng.randn(leaves, groups, bins, 3)
                .astype(np.float32) * 100, 3)
# every rank holds ALL shards too, purely to pin the TCP result
# bit-exact against the host codec on the same inputs
shards = np.stack(tp.allgather_obj(hist), axis=0)
TELEMETRY.configure("counters")
out = {}
for mode in ("f32", "q16", "q8"):
    TELEMETRY.reset()
    t0 = time.time()
    for _ in range(reps):
        res = tp.exchange_histograms(hist, mode)
    wall = (time.time() - t0) / reps
    ref = host_exchange_histograms(shards, mode)
    if not np.array_equal(res, ref):
        raise SystemExit(f"hist_exchange {mode} over TCP is not "
                         "bit-exact vs the host codec")
    c = TELEMETRY.counters()
    out[mode] = {
        "payload_wire_bytes":
            int(c.get("collective_tcp_hist_exchange_bytes", 0)) // reps,
        "scale_wire_bytes":
            int(c.get("collective_tcp_hist_scale_bytes", 0)) // reps,
        "total_wire_bytes":
            int(c.get("collective_tcp_bytes", 0)) // reps,
        "rounds": int(c.get("collective_tcp_rounds", 0)) // reps,
        "wall_ms": round(wall * 1e3, 2),
    }
# frame-CRC cost on the q16 wire path, measured two ways: the
# ANALYTIC fraction (the actual payload digest timed over exactly the
# q16 wire volume at the real per-frame granularity, divided by the
# q16 round wall — robust to 1-core scheduler jitter) is the <2%
# gate; the on/off wall delta is informational only
wire = int(out["q16"]["payload_wire_bytes"]) \
    + int(out["q16"]["scale_wire_bytes"])
nframes = max(2 * int(out["q16"]["rounds"]), 1)
frame = bytes(max(wire // nframes, 1))
crc_reps = max(reps, 5)
t0 = time.time()
for _ in range(crc_reps):
    for _ in range(nframes):
        T._payload_crc(frame)
crc_s = (time.time() - t0) / crc_reps
T._FRAME_CRC = False
t0 = time.time()
for _ in range(reps):
    tp.exchange_histograms(hist, "q16")
nocrc_wall = (time.time() - t0) / reps
T._FRAME_CRC = True
out["crc"] = {
    "q16_wire_bytes": wire,
    "crc_ms": round(crc_s * 1e3, 3),
    "crc_frac_of_q16_wall": round(
        crc_s / max(out["q16"]["wall_ms"] / 1e3, 1e-9), 4),
    "q16_wall_ms_nocrc": round(nocrc_wall * 1e3, 2),
}
tp.close()
if pid == 0:
    print(json.dumps(out))
"""


def run_distributed_exchange(params):
    """Distributed-exchange roofline point (this round): the r21
    hist_exchange codec over the REAL host-side TCP transport — two
    processes, real sockets — reporting per-mode wire bytes from the
    ``collective_tcp_*`` per-primitive counters and gating the q16
    payload at >=2x (q8 >=4x) the f32 wire frames, every mode pinned
    bit-exact against ``host_exchange_histograms`` inside the workers.

    Two honest byte views: ``payload`` counts the frames that carry
    histogram data (f32 allgather vs the int16/int8 ring); ``total``
    adds the q-modes' one pmax scale-sync round.  At world=2 the ring
    and the allgather both move the whole array once, so the total
    ratio reads just under the dtype ratio — it grows toward
    world_size at larger worlds, where the f32 allgather pays
    (P-1) full copies and the integer ring stays ~2 copies."""
    import socket
    import subprocess

    leaves = int(os.environ.get("BENCH_DIST_LEAVES", 31))
    groups = int(os.environ.get("BENCH_DIST_GROUPS", 28))
    bins = int(os.environ.get("BENCH_DIST_BINS", 64))
    reps = int(os.environ.get("BENCH_DIST_REPS", 3))
    s = socket.socket()
    s.bind(("localhost", 0))
    coord = f"localhost:{s.getsockname()[1]}"
    s.close()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _DIST_EXCHANGE_WORKER, coord, "2",
         str(i), str(leaves), str(groups), str(bins), str(reps)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(2)]
    outs = []
    for p in procs:
        try:
            o, e = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise SystemExit("distributed_exchange bench hung")
        if p.returncode != 0:
            raise SystemExit(
                f"distributed_exchange worker failed: {e[-1500:]}")
        outs.append(o)
    modes = json.loads(outs[0].strip().splitlines()[-1])
    crc = modes.pop("crc")
    ratio16 = modes["f32"]["payload_wire_bytes"] \
        / max(modes["q16"]["payload_wire_bytes"], 1)
    ratio8 = modes["f32"]["payload_wire_bytes"] \
        / max(modes["q8"]["payload_wire_bytes"], 1)
    if ratio16 < 2.0 or ratio8 < 4.0:
        raise SystemExit(
            f"distributed_exchange wire gate failed: q16 {ratio16:.2f}x"
            f" (need >=2.0), q8 {ratio8:.2f}x (need >=4.0) vs f32")
    if crc["crc_frac_of_q16_wall"] >= 0.02:
        raise SystemExit(
            "distributed_exchange crc gate failed: frame-CRC costs "
            f"{crc['crc_frac_of_q16_wall'] * 100:.2f}% of the q16 "
            "wire path (budget <2%)")
    return {
        "task": "distributed_exchange", "world": 2,
        "hist_shape": [leaves, groups, bins, 3],
        "modes": modes,
        "wire_ratio_q16": round(ratio16, 2),
        "wire_ratio_q8": round(ratio8, 2),
        "total_wire_ratio_q16": round(
            modes["f32"]["total_wire_bytes"]
            / max(modes["q16"]["total_wire_bytes"], 1), 2),
        "parity": "pass",
        "wire_gate": "pass",
        "crc": crc,
        "crc_overhead_frac": crc["crc_frac_of_q16_wall"],
        "crc_gate": "pass",
    }


def run_compact_bins(params, rows=None):
    """Sub-byte packed bin matrix roofline point (round 18, ROADMAP
    item 4): the nibble-packed (bin_packing=4bit) pipeline measured
    against the 8-bit one on the same max_bin=15 draw.

    Reports construct rows/s per mode (the pack adds one fused
    byte-combine pass over each chunk — gate: within ~0.9x), the
    HOST matrix bytes and the GAUGE-measured device bin-matrix bytes
    (``bin_matrix_bytes``, rows_padded x storage cols), and an
    analytic histogram bytes-read-per-row model (the packed stream the
    tiled kernels actually read).  Hard gates: >= 2x packing ratio at
    max_bin=15 (28 dense feature groups -> exactly 2x) and
    byte-identical trees across modes."""
    import re as _re

    import lightgbm_tpu as lgb
    from lightgbm_tpu.telemetry import TELEMETRY

    if rows is None:        # standalone use; main() passes the
        rows = int(os.environ.get("BENCH_COMPACT_ROWS",  # admitted count
                                  min(BENCH_ROWS, 500_000)))
    X, y, _ = make_data(rows, BENCH_FEATURES, seed=43)
    base = {"objective": "binary", "num_leaves": params["num_leaves"],
            "max_bin": 15, "num_iterations": 2, "min_data_in_leaf": 5,
            "telemetry": "counters", "verbose": -1}

    out = {"task": "compact_bins", "rows": rows,
           "features": BENCH_FEATURES, "max_bin": 15}
    host_bytes = {}
    device_bytes = {}
    trees = {}
    for mode in ("8bit", "4bit"):
        p = dict(base, bin_packing=mode)
        gc.collect()
        rss0 = _rss_mb()
        t0 = time.time()
        dset = lgb.Dataset(X, label=y).construct(
            lgb.config.Config.from_params(p))
        construct_s = time.time() - t0
        host_bytes[mode] = int(np.asarray(dset.group_bins).nbytes)
        out[f"construct_s_{mode}"] = round(construct_s, 3)
        out[f"construct_rows_per_s_{mode}"] = round(
            rows / max(construct_s, 1e-9))
        out[f"rss_delta_mb_{mode}"] = round(
            max(0.0, _rss_mb() - rss0), 1)
        wrapped = lgb.Dataset(X, label=y, params=p)
        wrapped._core = dset
        booster = lgb.train(p, wrapped)
        g = TELEMETRY.snapshot().get("gauges", {})
        device_bytes[mode] = int(g.get("bin_matrix_bytes", 0))
        trees[mode] = _re.sub(r"\[bin_packing: \w+\]", "",
                              booster.model_to_string())
        del dset, wrapped, booster
        gc.collect()

    out["host_matrix_bytes_8bit"] = host_bytes["8bit"]
    out["host_matrix_bytes_4bit"] = host_bytes["4bit"]
    out["bin_matrix_bytes_8bit"] = device_bytes["8bit"]
    out["bin_matrix_bytes_4bit"] = device_bytes["4bit"]
    out["packing_ratio"] = round(
        host_bytes["8bit"] / max(host_bytes["4bit"], 1), 3)
    out["device_packing_ratio"] = round(
        device_bytes["8bit"] / max(device_bytes["4bit"], 1), 3)
    out["construct_ratio_4bit_vs_8bit"] = round(
        out["construct_rows_per_s_4bit"]
        / max(out["construct_rows_per_s_8bit"], 1), 3)
    # analytic histogram bytes-read model: the tiled/fused kernels
    # stream the (transposed) bin matrix + 16 weight/leaf bytes per
    # row per pass — packing halves the bins term, the whole
    # bandwidth story at max_bin <= 16
    g8, g4 = BENCH_FEATURES, (BENCH_FEATURES + 1) // 2
    out["hist_bytes_per_row_8bit"] = g8 + 16
    out["hist_bytes_per_row_4bit"] = g4 + 16
    out["hist_stream_ratio"] = round((g8 + 16) / (g4 + 16), 3)

    if out["packing_ratio"] < 2.0 - 1e-9:
        raise SystemExit(
            f"compact_bins packing gate failed: host ratio "
            f"{out['packing_ratio']} < 2.0 at max_bin=15 "
            f"({BENCH_FEATURES} dense groups must pack two per byte)")
    if device_bytes["8bit"] and device_bytes["4bit"] \
            and out["device_packing_ratio"] < 1.8:
        # padded rows are identical across modes, so the device ratio
        # only dips below 2.0 through an odd group count
        raise SystemExit(
            "compact_bins device gate failed: bin_matrix_bytes ratio "
            f"{out['device_packing_ratio']} < 1.8")
    if trees["8bit"] != trees["4bit"]:
        raise SystemExit("compact_bins parity gate failed: trees "
                         "differ between bin_packing=8bit and 4bit")

    # --- crumb tier (round 21): the same pipeline on a max_bin=4
    # sub-draw, where bin_packing=2bit stores FOUR groups per byte.
    # Gate: the measured host ratio must meet the layout-predicted
    # read-stream reduction G / ceil(G/4) exactly (same rows, the
    # packed matrix IS the kernels' read stream at max_bin <= 4).
    base4 = dict(base, max_bin=4)
    host4 = {}
    dev4 = {}
    trees4 = {}
    for mode in ("8bit", "2bit"):
        p = dict(base4, bin_packing=mode)
        gc.collect()
        t0 = time.time()
        dset = lgb.Dataset(X, label=y).construct(
            lgb.config.Config.from_params(p))
        construct_s = time.time() - t0
        host4[mode] = int(np.asarray(dset.group_bins).nbytes)
        out[f"construct_rows_per_s_{mode}_mb4"] = round(
            rows / max(construct_s, 1e-9))
        wrapped = lgb.Dataset(X, label=y, params=p)
        wrapped._core = dset
        booster = lgb.train(p, wrapped)
        g = TELEMETRY.snapshot().get("gauges", {})
        dev4[mode] = int(g.get("bin_matrix_bytes", 0))
        trees4[mode] = _re.sub(r"\[bin_packing: \w+\]", "",
                               booster.model_to_string())
        del dset, wrapped, booster
        gc.collect()
    g2 = (BENCH_FEATURES + 3) // 4
    out["host_matrix_bytes_8bit_mb4"] = host4["8bit"]
    out["host_matrix_bytes_2bit"] = host4["2bit"]
    out["bin_matrix_bytes_2bit"] = dev4["2bit"]
    out["crumb_packing_ratio"] = round(
        host4["8bit"] / max(host4["2bit"], 1), 3)
    out["crumb_predicted_ratio"] = round(BENCH_FEATURES / g2, 3)
    out["crumb_device_ratio"] = round(
        dev4["8bit"] / max(dev4["2bit"], 1), 3)
    out["hist_bytes_per_row_2bit"] = g2 + 16
    out["crumb_stream_ratio"] = round(
        (BENCH_FEATURES + 16) / (g2 + 16), 3)
    if out["crumb_packing_ratio"] < out["crumb_predicted_ratio"] - 1e-9:
        raise SystemExit(
            "compact_bins crumb gate failed: host ratio "
            f"{out['crumb_packing_ratio']} below the layout-predicted "
            f"{out['crumb_predicted_ratio']} at max_bin=4")
    if trees4["8bit"] != trees4["2bit"]:
        raise SystemExit("compact_bins parity gate failed: trees "
                         "differ between bin_packing=8bit and 2bit")

    # --- compressed histogram exchange (round 21): the q16/q8 codec's
    # measured wire bytes through the SAME host collective path the
    # sharded windows ride, via its telemetry counters.  Gate: q16
    # halves and q8 quarters the f32 payload.
    from lightgbm_tpu.parallel.collectives import host_exchange_histograms
    TELEMETRY.configure("counters")
    rng_h = np.random.RandomState(47)
    shard_hists = [
        np.cumsum(rng_h.randint(-15, 16,
                                size=(params["num_leaves"],
                                      BENCH_FEATURES, 16, 3)),
                  axis=-2).astype(np.float32)
        for _ in range(2)]
    for mode in ("f32", "q16", "q8"):
        TELEMETRY.reset()
        host_exchange_histograms(shard_hists, mode=mode)
        c = TELEMETRY.snapshot().get("counters", {})
        out[f"hist_exchange_bytes_{mode}"] = int(
            c.get("collective_hist_exchange_bytes", 0))
    out["hist_exchange_ratio_q16"] = round(
        out["hist_exchange_bytes_f32"]
        / max(out["hist_exchange_bytes_q16"], 1), 3)
    out["hist_exchange_ratio_q8"] = round(
        out["hist_exchange_bytes_f32"]
        / max(out["hist_exchange_bytes_q8"], 1), 3)
    if out["hist_exchange_ratio_q16"] < 2.0 - 1e-9 \
            or out["hist_exchange_ratio_q8"] < 4.0 - 1e-9:
        raise SystemExit(
            "compact_bins hist_exchange gate failed: byte reduction "
            f"q16 {out['hist_exchange_ratio_q16']}x / q8 "
            f"{out['hist_exchange_ratio_q8']}x (need 2x / 4x)")
    out["parity"] = "pass"
    return out


def run_predict_scale(params):
    """Serving roofline point: bulk scoring throughput, micro-batch
    p50 latency and the compile count of the shape-bucketed device
    predictor, gated on exact parity with the host tree walk and
    anchored against the reference CPU ``task=predict``.

    Runs with ``device=True`` so the measurement exercises the device
    predictor on whatever backend JAX selected (``backend`` is
    recorded; on the CPU seam the numbers are the XLA-CPU analog of
    the on-chip run, same as the training scales)."""
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops.predict import (PREDICT_TELEMETRY,
                                          reset_predict_telemetry)

    train_rows = int(os.environ.get("BENCH_PREDICT_TRAIN_ROWS", 200_000))
    iters = int(os.environ.get("BENCH_PREDICT_ITERS", 50))
    bulk_rows = int(os.environ.get("BENCH_PREDICT_ROWS", 2_000_000))
    small = int(os.environ.get("BENCH_PREDICT_SMALL_BATCH", 32))
    calls = int(os.environ.get("BENCH_PREDICT_CALLS", 50))

    X, y, w = make_data(train_rows, BENCH_FEATURES, seed=21)
    bst = lgb.train(dict(params), lgb.Dataset(X, label=y), iters,
                    verbose_eval=False)
    n_trees = bst.num_trees()
    Xb, yb, _ = make_data(bulk_rows, BENCH_FEATURES, seed=22, w=w)
    del X, y
    gc.collect()

    reset_predict_telemetry()
    # warm pass compiles every bucket the measurement will touch
    t0 = time.time()
    bst.predict(Xb[:small], device=True)
    pred = bst.predict(Xb, device=True)
    warm_s = time.time() - t0
    t0 = time.time()
    pred = bst.predict(Xb, device=True)
    bulk_s = time.time() - t0

    # parity gate: the serving numbers are only evidence if the device
    # predictor routes every row exactly like the host walk
    n_check = min(4096, bulk_rows)
    host = bst.predict(Xb[:n_check], device=False)
    if not np.allclose(pred[:n_check], host, rtol=2e-5, atol=2e-7):
        raise SystemExit(
            "device predict diverged from the host tree walk on the "
            f"bench draw (max |delta| "
            f"{np.max(np.abs(pred[:n_check] - host)):g}) — serving "
            "parity gate failed")

    lat = []
    off = 0
    for _ in range(calls):
        t0 = time.time()
        bst.predict(Xb[off:off + small], device=True)
        lat.append(time.time() - t0)
        off = (off + small) % max(bulk_rows - small, 1)
    p50_ms = float(np.percentile(np.asarray(lat) * 1e3, 50))

    buckets = sorted(PREDICT_TELEMETRY["buckets"])
    out = {
        "task": "predict", "backend": jax.default_backend(),
        "model_trees": n_trees, "model_leaves": params["num_leaves"],
        "rows": bulk_rows,
        "bulk_rows_per_s": round(bulk_rows / bulk_s),
        "bulk_s": round(bulk_s, 3),
        "warm_s": round(warm_s, 3),
        "small_batch": small,
        "p50_ms": round(p50_ms, 3),
        "compile_count": PREDICT_TELEMETRY["traces"],
        "buckets_used": buckets,
        "dispatches": PREDICT_TELEMETRY["dispatches"],
        "parity": "pass",
    }
    anchor_rows = min(bulk_rows,
                      int(os.environ.get("BENCH_PREDICT_ANCHOR_ROWS",
                                         200_000)))
    ref = run_local_reference_predict(
        bst.model_to_string(), Xb[:anchor_rows], yb[:anchor_rows],
        params, n_trees)
    if ref is None:
        out["local_ref_skipped"] = "BENCH_LOCAL_REF[_PREDICT]=0"
    elif "skipped" in ref:
        out["local_ref_skipped"] = ref["skipped"]
    else:
        out["local_ref"] = ref
        out["vs_local_reference"] = round(
            out["bulk_rows_per_s"] / ref["rows_per_s"], 3)
    return out


def run_higgs_real(params):
    """Real-HIGGS anchor (round-4 verdict #6): when the UCI HIGGS
    dataset is available — BENCH_HIGGS_PATH pointing at HIGGS.csv[.gz],
    or BENCH_HIGGS=1 to attempt the UCI download — train the bench
    config on the true data and report held-out AUC against the
    reference's published 0.845 (docs/Experiments.rst:125-129, last
    500k rows held out per the experiment's convention).  Returns the
    scale dict, or None with a stderr note when the data cannot be
    obtained (this image has zero egress, so the download attempt
    documents the impossibility rather than working around it)."""
    import gzip

    path = os.environ.get("BENCH_HIGGS_PATH")
    if not path and os.environ.get("BENCH_HIGGS") == "1":
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".data", "HIGGS.csv.gz")
        if not os.path.exists(path):
            url = ("https://archive.ics.uci.edu/ml/machine-learning-"
                   "databases/00280/HIGGS.csv.gz")
            try:
                import urllib.request
                os.makedirs(os.path.dirname(path), exist_ok=True)
                urllib.request.urlretrieve(url, path + ".part")
                os.replace(path + ".part", path)
            except Exception as e:
                print(f"real-HIGGS download failed ({type(e).__name__}:"
                      f" {e}) — this environment has no egress; "
                      "synthetic-only caveat stands (BASELINE.md)",
                      file=sys.stderr)
                return None
    if not path or not os.path.exists(path):
        return None

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        arr = np.loadtxt(f, delimiter=",", dtype=np.float32)
    y, X = arr[:, 0], arr[:, 1:]
    Xt, yt = X[-500_000:], y[-500_000:]
    X, y = X[:-500_000], y[:-500_000]
    import lightgbm_tpu as lgb
    gbdt, cfg, dtrain, prep_s, timing = train_timed(
        params, X, y, int(os.environ.get("BENCH_HIGGS_ITERS", 100)))
    vcore = lgb.Dataset(Xt, label=yt, reference=dtrain).construct(cfg)
    auc = auc_score(yt, heldout_scores(gbdt, cfg, vcore.group_bins))
    return attach_timing(
        {"rows": int(X.shape[0]), "task": "higgs_real",
         "auc": round(auc, 6), "auc_published_ref": 0.845154,
         "per_tree_ms": round(timing["per_tree"] * 1e3, 2),
         "prep_s": round(prep_s, 3)}, timing)


def run_scale(rows, iters, params, check_f32, local_ref=False,
              ref_iters=None, slope_probe=False):
    """Train + evaluate one scale point; returns its metrics dict."""
    import lightgbm_tpu as lgb

    X, y, w = make_data(rows, BENCH_FEATURES)
    Xv, yv, _ = make_data(VALID_ROWS, BENCH_FEATURES, seed=8, w=w)
    gbdt, cfg, dtrain, prep_s, timing = train_timed(
        params, X, y, iters)
    compile_s = timing["compile_s"]
    per_tree = timing["per_tree"]
    cold_total_s = timing["cold_total_s"]
    total_equiv = per_tree * iters
    vcore = lgb.Dataset(Xv, label=yv, reference=dtrain).construct(cfg)
    auc = auc_score(yv, heldout_scores(gbdt, cfg, vcore.group_bins))
    if slope_probe:
        # AFTER the headline timing and the held-out AUC: the probe
        # appends 2·Σprobes real trees to THIS model only, and the f32
        # comparison below trains exactly `iters` — probing earlier
        # would put an ensemble-size mismatch inside the 1e-3 gate
        timing["chunk_slope"] = chunk_slope_probe(gbdt)

    auc_f32 = auc
    if check_f32 and params.get("quantized_grad"):
        # free the timed run's device state (streamed one-hot etc.)
        # before the second training run — two runs' buffers don't
        # co-reside in HBM
        del gbdt, dtrain, vcore
        gc.collect()
        p32 = dict(params, quantized_grad=False)
        g32, c32, d32, _, _ = train_timed(p32, X, y, iters)
        v32 = lgb.Dataset(Xv, label=yv, reference=d32).construct(c32)
        auc_f32 = auc_score(yv, heldout_scores(g32, c32, v32.group_bins))
        del g32, d32, v32
    else:
        del gbdt, dtrain, vcore
    gc.collect()

    delta = abs(auc - auc_f32)
    if not (delta <= 1e-3):  # catches NaN too; survives python -O
        raise SystemExit(
            f"quantized AUC ({auc}) drifted {delta!r} from the f32 path "
            f"({auc_f32}) — over the 1e-3 reference GPU-vs-CPU tolerance")

    ref_scaled = REF_SEC_PER_TREE_ROW * rows * iters
    out = {
        "rows": rows,
        "iters": iters,
        "value": round(total_equiv, 3),
        "vs_baseline": round(ref_scaled / total_equiv, 3),
        "auc": round(auc, 6),
        "auc_f32": round(auc_f32, 6),
        "auc_delta": round(delta, 6),
        "prep_s": round(prep_s, 3),
        "compile_s": round(compile_s, 3),
        "cold_total_s": round(cold_total_s, 3),
        "per_tree_ms": round(per_tree * 1e3, 2),
    }
    attach_timing(out, timing)
    if local_ref:
        if ref_iters is None:
            ref_iters = int(os.environ.get("BENCH_REF_ITERS",
                                           min(iters, 30)))
        ref = run_local_reference(X, y, Xv, yv, params, ref_iters,
                                  task="binary", seed=7)
        attach_local_ref(out, ref, per_tree)
    return out


def _bench_wall_key() -> str:
    # keyed by workload shape like every other anchor: a unit measured
    # at leaves=15/max_bin=31 (CI config) is off by the per-tree cost
    # ratio for a 255/63 perf run — admission would then re-admit the
    # exact overrun it exists to prevent
    return (f"bench_wall:host={_host_tag()}:nl={NUM_LEAVES}"
            f":mb={MAX_BIN}")


def admit_primary(rows, iters):
    """Round-13: the PRIMARY scale itself is budget-admitted (the r5
    rc=124 record — BENCH_r05.json ``parsed: null`` — was a
    measurement run escaping admission and blowing the outer driver
    timeout; r8 budgeted every phase EXCEPT the first one).  The
    estimate comes from this bench's own measured wall on this host,
    persisted under the ``bench_wall:`` key in LOCAL_REF.json — the
    first run on a host has no estimate and runs as configured, every
    later run scales the primary rows DOWN to what the budget fits
    (with a ``scaled_down_from`` note) instead of starting a run that
    cannot finish.  Returns (admitted_rows, note-or-None)."""
    rec = _local_ref_load().get(_bench_wall_key())
    if _bench_wall_key() in _LOCAL_REF_BAD or not isinstance(rec, dict):
        return rows, None
    try:
        unit = float(rec.get("unit_s_per_row_iter", 0) or 0)
        fixed = float(rec.get("fixed_s", 0) or 0)
    except (TypeError, ValueError):
        return rows, None
    if unit <= 0:
        return rows, None
    left = budget_left() - FINISH_RESERVE_S
    est = fixed + 1.3 * unit * rows * iters
    if est <= left:
        return rows, None
    rows_fit = int(max(0.0, left - fixed) / (1.3 * unit * max(iters, 1)))
    # floor INSIDE the configured rows: max-then-min would scale a
    # 2048-row primary UP to 4096 and mislabel it scaled_down_from
    rows_fit = min(rows, max(4096, rows_fit))
    note = (f"BENCH_BUDGET_S primary admission: est {est:.0f}s > "
            f"{left:.0f}s left (unit {unit:.3g} s/(row*iter) measured "
            f"on this host last run); rows {rows} -> {rows_fit}")
    return rows_fit, note


def _store_bench_wall(rows, iters, wall_s, compile_s) -> None:
    """Persist the measured primary wall as the next run's admission
    estimate (same-host only — the key carries the CPU model)."""
    fixed = max(0.0, float(compile_s))
    unit = max(wall_s - fixed, 1e-9) / max(rows * iters, 1)
    _local_ref_store(_bench_wall_key(), {
        "unit_s_per_row_iter": unit, "fixed_s": round(fixed, 3),
        "rows": int(rows), "iters": int(iters),
        "wall_s": round(wall_s, 3)})


def run_scale_boxed(rows, iters, params, check_f32, local_ref,
                    ref_iters, box_s, task):
    """Run one scale point in a TIME-BOXED subprocess (round 13): once
    admitted, a big measurement run used to be unkillable — if the
    admission estimate was optimistic (10.5M-row construction is
    superlinear under memory pressure) it blew the OUTER driver
    timeout and the whole bench died rc=124 with ``parsed: null``
    (BENCH_r05.json).  The box turns that worst case into a
    skip-with-note record: the child is killed at the box, the parent
    still emits its one-line JSON with rc 0.  ``BENCH_BIG_BOX_S``
    overrides the box (ops/test hook)."""
    import signal
    import subprocess
    box_s = max(3.0, float(os.environ.get("BENCH_BIG_BOX_S", box_s)))
    env = dict(os.environ)
    env["BENCH_CHILD_SCALE"] = json.dumps(
        {"rows": int(rows), "iters": int(iters),
         "check_f32": bool(check_f32), "local_ref": bool(local_ref),
         "ref_iters": ref_iters})
    env["BENCH_CHILD_PARAMS"] = json.dumps(params)
    # own session/process GROUP: on box expiry the kill must reach the
    # child's own subprocesses too (a fresh local_ref anchor spawns the
    # reference binary — orphaning it would leave minutes of training
    # burning CPU under every remaining bench phase, the exact
    # contention the box exists to prevent)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        out, err = proc.communicate(timeout=box_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out, err = proc.communicate()
        if err:
            print(err, file=sys.stderr, end="")
        return {"task": task, "rows": int(rows),
                "skipped": f"scale run hit its {box_s:.0f}s time box "
                           "(admission estimate too optimistic); the "
                           "r5 rc=124 escape is contained to this "
                           "skip note"}
    if err:
        print(err, file=sys.stderr, end="")
    lines = [ln for ln in (out or "").strip().splitlines()
             if ln.strip()]
    if proc.returncode != 0 or not lines:
        return {"task": task, "rows": int(rows),
                "skipped": f"scale child exited rc {proc.returncode}: "
                           f"{(err or '')[-300:]}"}
    try:
        return json.loads(lines[-1])
    except ValueError:
        return {"task": task, "rows": int(rows),
                "skipped": "scale child emitted unparseable output: "
                           f"{lines[-1][:200]}"}


def main():
    # time-boxed child mode (run_scale_boxed): run ONE scale point and
    # print its record as the single stdout JSON line
    child = os.environ.get("BENCH_CHILD_SCALE")
    if child:
        spec = json.loads(child)
        params = json.loads(os.environ["BENCH_CHILD_PARAMS"])
        notes, bad = validate_local_ref()
        _LOCAL_REF_NOTES.extend(notes)
        _LOCAL_REF_BAD.update(bad)
        out = run_scale(spec["rows"], spec["iters"], params,
                        spec["check_f32"],
                        local_ref=spec["local_ref"],
                        ref_iters=spec.get("ref_iters"))
        print(json.dumps(out))
        return

    # the persistent compilation cache is wired by the library itself
    # (config.compile_cache_dir, default ~/.cache/lightgbm_tpu/jit) —
    # the first Config created below applies it and logs hit/miss
    params = {
        "objective": "binary", "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN, "learning_rate": 0.1, "verbose": -1,
        "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0,
        "hist_compute_dtype": os.environ.get("BENCH_HIST_DTYPE",
                                             "bfloat16"),
        # int8-MXU quantized histograms — the TPU analog of the
        # reference benchmarking its single-precision 63-bin GPU path
        # (docs/GPU-Performance.rst:134-161); the JSON line reports the
        # held-out AUC of this path AND the f32 path at the primary
        # scale, asserting the delta stays within the reference's own
        # GPU-vs-CPU tolerance of 1e-3.  Disable with BENCH_QUANTIZED=0.
        "quantized_grad": os.environ.get("BENCH_QUANTIZED", "1") != "0",
    }
    # ad-hoc experiment overrides, e.g. BENCH_PARAMS='{"frontier_width":64}'
    extra = os.environ.get("BENCH_PARAMS")
    if extra:
        params.update(json.loads(extra))

    # anchor-cache validation BEFORE any scale consults LOCAL_REF.json:
    # drifted keys/records become stderr skip-notes and are never
    # served (round-7 satellite; silently anchoring against a stale
    # key set was the failure mode)
    notes, bad = validate_local_ref()
    _LOCAL_REF_NOTES.extend(notes)
    _LOCAL_REF_BAD.update(bad)
    for n in notes:
        print(f"LOCAL_REF validation: {n}", file=sys.stderr)

    check_f32 = os.environ.get("BENCH_SKIP_F32") != "1"
    # round 13: the primary scale is budget-admitted too — scaled down
    # against the bench_wall unit measured on this host last run
    rows_primary, primary_note = admit_primary(BENCH_ROWS, BENCH_ITERS)
    if primary_note:
        print(f"primary admission: {primary_note}", file=sys.stderr)
    t_primary = time.time()
    primary = run_scale(
        rows_primary, BENCH_ITERS, params, check_f32, local_ref=True,
        slope_probe=os.environ.get("BENCH_SLOPE_PROBE", "1") != "0")
    primary_wall = max(time.time() - t_primary, 1e-3)
    if primary_note:
        primary["scaled_down_from"] = BENCH_ROWS
        primary["budget_note"] = primary_note
    if os.environ.get("BENCH_LOCAL_REF", "1") != "0":
        # persist the measured wall as the next run's admission
        # estimate — but never from the tiny-N smoke driver
        # (BENCH_LOCAL_REF=0): its compile-dominated unit would make
        # the next perf run scale down a primary that actually fits
        _store_bench_wall(rows_primary, BENCH_ITERS, primary_wall,
                          primary.get("compile_s", 0.0))
    scales = [primary]

    # ---- per-phase budget admission (round 8): every REMAINING phase
    # is admitted against an estimate scaled from the measured primary
    # wall, so a lightgbm_tpu measurement run can no longer blow the
    # outer driver timeout the way the r5 10.5M run did (rc=124,
    # BENCH_r05.json parsed: null).  Estimates are deliberately
    # conservative (1.5x) — a phase that would overrun is scaled down
    # (big scale) or skipped WITH A NOTE, never started and killed.
    # Round 13 closes the remaining escape: an ADMITTED big run is
    # additionally time-boxed in a subprocess (run_scale_boxed), so an
    # optimistic estimate degrades to a skip note instead of rc=124.

    def admit(task, est_s):
        """Remaining-budget admission for one phase; returns the skip
        note (None = run it)."""
        left = budget_left() - FINISH_RESERVE_S
        if est_s <= left:
            return None
        return (f"BENCH_BUDGET_S phase bound: est {est_s:.0f}s > "
                f"{left:.0f}s left")

    if os.environ.get("BENCH_BIG", "1") != "0" \
            and BENCH_ROWS_BIG > rows_primary:
        # HIGGS true scale: the f32 accuracy gate already ran at the
        # primary scale (same kernels, same quantization); rerunning
        # two 10.5M trainings would double the bench wall for no new
        # information.
        # local_ref at true scale too (round-4 verdict #5: the 34.1x
        # 10.5M ratio was prose-only — capture it in the JSON record).
        # Unit is per (row * iter) — the r8 estimate silently assumed
        # BENCH_ITERS_BIG == BENCH_ITERS
        big_wall_unit = primary_wall * 1.5 \
            / (rows_primary * max(BENCH_ITERS, 1))
        rows_big = BENCH_ROWS_BIG
        est = big_wall_unit * rows_big * max(BENCH_ITERS_BIG, 1)
        note = admit("big", est)
        if note is not None:
            # scale the row count down to what the budget fits (floor
            # 2x primary — below that the point adds nothing)
            rows_fit = int((budget_left() - FINISH_RESERVE_S)
                           / (big_wall_unit * max(BENCH_ITERS_BIG, 1)))
            rows_big = rows_fit if rows_fit >= 2 * rows_primary else 0
        if rows_big:
            box = max(10.0, budget_left() - FINISH_RESERVE_S)
            s = run_scale_boxed(
                rows_big, BENCH_ITERS_BIG, params, check_f32=False,
                local_ref=os.environ.get("BENCH_LOCAL_REF_BIG",
                                         "1") != "0",
                ref_iters=int(os.environ.get("BENCH_REF_ITERS_BIG",
                                             10)),
                box_s=box, task="binary_big")
            if rows_big != BENCH_ROWS_BIG and "skipped" not in s:
                s["scaled_down_from"] = BENCH_ROWS_BIG
                s["budget_note"] = note
            scales.append(s)
        else:
            scales.append({"task": "binary_big", "rows": BENCH_ROWS_BIG,
                           "skipped": note})
    if os.environ.get("BENCH_LTR", "1") != "0":
        ltr_rows = int(os.environ.get("BENCH_LTR_QUERIES", 18_900)) * 120
        ltr_iters = int(os.environ.get("BENCH_LTR_ITERS", 30))
        # width factor: MS-LTR is 136 features vs the 28-feature
        # primary; anchors self-box against the remaining budget
        est = (primary_wall * 1.5 * (136 / 28)
               * (ltr_rows * ltr_iters) / (rows_primary * BENCH_ITERS))
        note = admit("lambdarank", est)
        if note is None:
            scales.append(run_ltr_scale())
        else:
            scales.append({"task": "lambdarank", "skipped": note})
    predict_block = None
    if os.environ.get("BENCH_PREDICT", "1") != "0":
        p_rows = int(os.environ.get("BENCH_PREDICT_TRAIN_ROWS", 200_000))
        p_iters = int(os.environ.get("BENCH_PREDICT_ITERS", 50))
        est = (primary_wall * 1.5
               * (p_rows * p_iters) / (rows_primary * BENCH_ITERS)) + 30
        note = admit("predict", est)
        if note is None:
            predict_block = run_predict_scale(params)
        else:
            predict_block = {"task": "predict", "skipped": note}
    construct_block = None
    if os.environ.get("BENCH_CONSTRUCT", "1") != "0":
        c_rows = int(os.environ.get("BENCH_CONSTRUCT_ROWS",
                                    min(BENCH_ROWS, 1_000_000)))
        # three constructions (serial python, parallel, threads=1) + a
        # cache round trip; the serial Python pass dominates at
        # ~3-5 s/M rows on one core — 20 s/M is a safe ceiling
        est = max(10.0, 20.0 * c_rows / 1e6)
        note = admit("construct", est)
        if note is None:
            construct_block = run_construct_scale(params)
        else:
            construct_block = {"task": "construct", "rows": c_rows,
                               "skipped": note}
    shard_block = None
    if os.environ.get("BENCH_SHARD", "1") != "0":
        s_rows = int(os.environ.get("BENCH_SHARD_ROWS",
                                    min(BENCH_ROWS, 500_000)))
        # two constructions (single-matrix + sharded) + a standalone
        # merge pass + a cache round trip; same per-row ceiling as the
        # construct block, doubled
        est = max(10.0, 40.0 * s_rows / 1e6)
        note = admit("shard_construct", est)
        if note is None:
            shard_block = run_shard_construct(params)
        else:
            shard_block = {"task": "shard_construct", "rows": s_rows,
                           "skipped": note}
    dist_block = None
    if os.environ.get("BENCH_DIST", "1") != "0":
        # two CPU-pinned worker interpreters + three tiny exchanges:
        # the wall is import-dominated (~20 s on one core), not
        # data-dependent
        note = admit("distributed_exchange", 60.0)
        if note is None:
            dist_block = run_distributed_exchange(params)
        else:
            dist_block = {"task": "distributed_exchange",
                          "skipped": note}
    compact_block = None
    if os.environ.get("BENCH_COMPACT", "1") != "0":
        cb_rows = int(os.environ.get("BENCH_COMPACT_ROWS",
                                     min(BENCH_ROWS, 500_000)))
        # two constructions + two tiny (2-iteration) trainings; same
        # per-row ceiling as the construct block, doubled for the two
        # modes
        est = max(10.0, 40.0 * cb_rows / 1e6)
        note = admit("compact_bins", est)
        if note is None:
            # the admitted cb_rows feeds the run too, so admission and
            # workload can never diverge
            compact_block = run_compact_bins(params, rows=cb_rows)
        else:
            compact_block = {"task": "compact_bins", "rows": cb_rows,
                             "skipped": note}
    if budget_left() > 60 + FINISH_RESERVE_S:
        higgs = run_higgs_real(params)
        if higgs is not None:
            scales.append(higgs)
    elif os.environ.get("BENCH_HIGGS_PATH") \
            or os.environ.get("BENCH_HIGGS") == "1":
        # the real-HIGGS scale was REQUESTED but the budget is spent —
        # document the hole instead of silently dropping the point
        scales.append({"task": "higgs_real",
                       "skipped": "BENCH_BUDGET_S exhausted"})

    result = {
        "metric": f"higgs_synth_{rows_primary//1000}k_{BENCH_ITERS}trees_s",
        "value": primary["value"],
        "unit": "s",
        "vs_baseline": primary["vs_baseline"],
        "auc": primary["auc"],
        "auc_f32": primary["auc_f32"],
        "auc_delta": primary["auc_delta"],
        "prep_s": primary["prep_s"],
        "compile_s": primary["compile_s"],
        "cold_total_s": primary["cold_total_s"],
        # ROOFLINE headroom #3 series: device wait vs host/dispatch
        # wall, per tree, at the primary scale
        "host_dispatch_ms_per_tree": primary["host_dispatch_ms_per_tree"],
        "device_wait_ms_per_tree": primary["device_wait_ms_per_tree"],
        "scales": scales,
        "budget": {"budget_s": BENCH_BUDGET_S,
                   "elapsed_s": round(time.time() - _T0, 1)},
    }
    if predict_block is not None:
        # the serving roofline block: bulk rows/s, micro-batch p50,
        # compile count (one per shape bucket) and the task=predict
        # anchor status (docs/ROOFLINE.md "Serving roofline")
        result["predict"] = predict_block
    if construct_block is not None:
        # the construction roofline block (round 11): cold-construct
        # rows/s parallel vs serial (same run), thread scaling, binary-
        # cache v2 reload ratio and the reference-CSV-load anchor
        # (docs/ROOFLINE.md round-11 delta)
        result["construct"] = construct_block
    if shard_block is not None:
        # the sharded-construct block (round 16): per-shard construct
        # rows/s, distributed bin-find merge wall, RSS per route,
        # shard-cache round trip — parity-gated against the
        # single-matrix construction inside the block
        result["shard_construct"] = shard_block
    if dist_block is not None:
        # the TCP distributed-exchange block (this round): per-mode
        # wire bytes over real sockets, q16/q8 payload-reduction gates
        # and host-codec bit-exactness — all enforced inside the block
        result["distributed_exchange"] = dist_block
    if compact_block is not None:
        # the sub-byte packed-bin block (round 18): construct rows/s
        # per bin width, host + gauge-measured device matrix bytes,
        # the histogram bytes-read model — packing-ratio- and
        # tree-parity-gated inside the block
        result["compact_bins"] = compact_block
    if "chunk_slope" in primary:
        # the round-6/7 per-iteration chunk-slope fit and what
        # dispatch_chunk=auto would pick locally and on an axon-RPC
        # host (the on-chip A/B expectation for the next session)
        result["chunk_slope"] = primary["chunk_slope"]
    if _LOCAL_REF_NOTES:
        result["local_ref_validation"] = _LOCAL_REF_NOTES
    if "vs_local_reference" in primary:
        # the MEASURED same-machine ratio (round-3 verdict #2): the
        # actual reference CPU binary on the same data on this host —
        # quote this one, the scaled 2013 number is only for continuity
        result["vs_local_reference"] = primary["vs_local_reference"]
        result["local_ref"] = primary["local_ref"]
    print(json.dumps(result))
    # diagnostics on stderr so the stdout contract stays one line
    # (defensive .get throughout: skip records and the higgs scale
    # don't carry the full field set, and a diagnostics KeyError must
    # never turn a completed bench into rc != 0)
    for s in scales:
        if "skipped" in s:
            print(f"{s.get('task', 'scale')} skipped: {s['skipped']}",
                  file=sys.stderr)
            continue
        if s.get("task") == "lambdarank":
            extra = ""
            if "vs_local_reference" in s:
                extra = (f" vs_local_ref={s['vs_local_reference']} "
                         f"(ref {s['local_ref']['per_tree_ms']}ms/tree @"
                         f"{s['local_ref']['threads']}thr ndcg10 "
                         f"{s['local_ref']['ndcg10']})")
            print(f"ltr rows={s['rows']} per_tree={s['per_tree_ms']}ms "
                  f"vs_baseline={s['vs_baseline']} "
                  f"ndcg10={s['ndcg10']} (untrained "
                  f"{s['ndcg10_untrained']}) prep={s['prep_s']}s{extra}",
                  file=sys.stderr)
            continue
        extra = ""
        if "vs_local_reference" in s:
            extra = (f" vs_local_ref={s['vs_local_reference']} "
                     f"(ref {s['local_ref']['per_tree_ms']}ms/tree @"
                     f"{s['local_ref']['threads']}thr auc "
                     f"{s['local_ref']['auc']})")
        print(f"rows={s.get('rows')} per_tree={s.get('per_tree_ms')}ms "
              f"vs_baseline={s.get('vs_baseline')} prep={s.get('prep_s')}s "
              f"compile={s.get('compile_s')}s{extra}", file=sys.stderr)
    if construct_block is not None:
        if "skipped" in construct_block:
            print(f"construct skipped: {construct_block['skipped']}",
                  file=sys.stderr)
        else:
            extra = ""
            if "vs_local_reference" in construct_block:
                extra = (f" vs_local_ref="
                         f"{construct_block['vs_local_reference']} (ref "
                         f"{construct_block['local_ref']['construct_s']}"
                         "s)")
            c = construct_block
            print(f"construct rows={c['rows']} "
                  f"cold={c['cold_construct_s']}s "
                  f"({c['cold_rows_per_s']} rows/s) "
                  f"serial={c['serial_construct_s']}s "
                  f"speedup={c['speedup_vs_serial']}x "
                  f"reload={c['cache_reload_s']}s "
                  f"({c['reload_x_cold']}x cold){extra}",
                  file=sys.stderr)
    if shard_block is not None:
        if "skipped" in shard_block:
            print(f"shard_construct skipped: {shard_block['skipped']}",
                  file=sys.stderr)
        else:
            sb = shard_block
            print(f"shard_construct rows={sb['rows']} "
                  f"shards={sb['shards']} "
                  f"wall={sb['shard_construct_s']}s "
                  f"({sb['per_shard_rows_per_s']} rows/s/shard) "
                  f"merge={sb['merge_wall_ms']}ms "
                  f"vs_single={sb['vs_single_matrix']}x "
                  f"rss={sb['rss_sharded_mb']}MB "
                  f"(single {sb['rss_single_mb']}MB)", file=sys.stderr)
    if compact_block is not None:
        if "skipped" in compact_block:
            print(f"compact_bins skipped: {compact_block['skipped']}",
                  file=sys.stderr)
        else:
            cb = compact_block
            print(f"compact_bins rows={cb['rows']} "
                  f"ratio={cb['packing_ratio']}x "
                  f"(device {cb['device_packing_ratio']}x) "
                  f"construct 4bit/8bit="
                  f"{cb['construct_ratio_4bit_vs_8bit']}x "
                  f"hist_stream={cb['hist_stream_ratio']}x "
                  f"parity={cb['parity']}", file=sys.stderr)
    if predict_block is not None:
        if "skipped" in predict_block:
            print(f"predict skipped: {predict_block['skipped']}",
                  file=sys.stderr)
        else:
            extra = ""
            if "vs_local_reference" in predict_block:
                extra = (f" vs_local_ref="
                         f"{predict_block['vs_local_reference']} (ref "
                         f"{predict_block['local_ref']['rows_per_s']} "
                         "rows/s)")
            print(f"predict bulk={predict_block['bulk_rows_per_s']} "
                  f"rows/s p50[{predict_block['small_batch']}]="
                  f"{predict_block['p50_ms']}ms "
                  f"compiles={predict_block['compile_count']} "
                  f"buckets={predict_block['buckets_used']}{extra}",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
