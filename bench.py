"""Benchmark: Higgs-like binary training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (BASELINE.md): the reference trains HIGGS
(10.5M rows x 28 features, 500 iters, 255 leaves) in 238.51 s on a
2x E5-2670v3 — 4.543e-8 s per (tree x row).  This harness trains a
synthetic 28-feature binary task at BENCH_ROWS x BENCH_ITERS with the
GPU-table config (63 bins, 255 leaves — docs/GPU-Performance.rst:108)
and reports wall-clock; vs_baseline = scaled_reference_time / ours
(>1 means faster than the reference CPU).
"""
import json
import os
import time

import numpy as np

BENCH_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
BENCH_FEATURES = 28
BENCH_ITERS = int(os.environ.get("BENCH_ITERS", 100))
NUM_LEAVES = 255
MAX_BIN = 63
REF_SEC_PER_TREE_ROW = 238.51 / (500 * 10_500_000)


def make_data(n, f, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f) * (rng.rand(f) > 0.3)
    logit = X[:, :f] @ w + 0.5 * np.sin(3 * X[:, 0]) * X[:, 1]
    y = (logit + rng.logistic(size=n) > 0).astype(np.float32)
    return X.astype(np.float64), y


def main():
    import jax
    # persistent compile cache: the fused training step costs minutes to
    # compile; cache hits make repeat bench runs start in seconds
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(
                              os.path.abspath(__file__)), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    X, y = make_data(BENCH_ROWS, BENCH_FEATURES)
    params = {
        "objective": "binary", "num_leaves": NUM_LEAVES,
        "max_bin": MAX_BIN, "learning_rate": 0.1, "verbose": -1,
        "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0,
        "hist_compute_dtype": os.environ.get("BENCH_HIST_DTYPE",
                                             "bfloat16"),
        # int8-MXU quantized histograms — the TPU analog of the
        # reference benchmarking its single-precision 63-bin GPU path
        # (docs/GPU-Performance.rst:134-161); measured AUC delta vs the
        # f32 path is ~1e-4, well inside the reference's GPU-vs-CPU
        # tolerance. Disable with BENCH_QUANTIZED=0.
        "quantized_grad": os.environ.get("BENCH_QUANTIZED", "1") != "0",
    }
    # ad-hoc experiment overrides, e.g. BENCH_PARAMS='{"frontier_width":64}'
    extra = os.environ.get("BENCH_PARAMS")
    if extra:
        params.update(json.loads(extra))
    cfg = Config.from_params(params)
    t0 = time.time()
    core = lgb.Dataset(X, label=y).construct(cfg)
    prep_s = time.time() - t0

    def drain():
        # jax.block_until_ready is not a reliable barrier on the
        # remote-attached (axon) TPU platform — force a device->host
        # read that depends on the full score state instead.
        np.asarray(gbdt.scores[:, :8])

    gbdt = GBDT(cfg, core)
    # multi-iteration fused chunks amortize the per-dispatch RPC cost
    # of the remote-attached TPU; same path engine.train uses headless
    chunk = max(1, min(int(os.environ.get("BENCH_CHUNK", 10)),
                       BENCH_ITERS // 2))
    # warmup: compile one chunk
    t0 = time.time()
    gbdt.train_chunk(chunk)
    drain()
    compile_s = time.time() - t0

    n_chunks = max(1, (BENCH_ITERS - chunk) // chunk)
    t0 = time.time()
    for _ in range(n_chunks):
        gbdt.train_chunk(chunk)
    drain()
    train_s = time.time() - t0
    per_tree = train_s / (n_chunks * chunk)
    total_equiv = per_tree * BENCH_ITERS

    ref_scaled = REF_SEC_PER_TREE_ROW * BENCH_ROWS * BENCH_ITERS
    result = {
        "metric": f"higgs_synth_{BENCH_ROWS//1000}k_{BENCH_ITERS}trees_s",
        "value": round(total_equiv, 3),
        "unit": "s",
        "vs_baseline": round(ref_scaled / total_equiv, 3),
    }
    print(json.dumps(result))
    # diagnostics on stderr so the stdout contract stays one line
    import sys
    print(f"prep={prep_s:.1f}s compile={compile_s:.1f}s "
          f"per_tree={per_tree*1000:.1f}ms ref_scaled={ref_scaled:.1f}s",
          file=sys.stderr)


if __name__ == "__main__":
    main()
